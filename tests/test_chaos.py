"""graftchaos tests: fault schedules, deterministic decisions/logs, the
chaos communication layer, the retry policy, and barrier diagnostics
(ISSUE 3 / docs/chaos.md)."""

import random
import threading
import time

import pytest

pytest.importorskip("jax")

from pydcop_tpu.chaos import (  # noqa: E402
    ChaosController,
    ChaosCommunicationLayer,
    DeviceFault,
    FaultSchedule,
    KillEvent,
    MessageRule,
    load_fault_schedule,
    unit_draw,
)
from pydcop_tpu.infrastructure.communication import (  # noqa: E402
    InProcessCommunicationLayer,
    Message,
    Messaging,
    UnreachableAgent,
)
from pydcop_tpu.infrastructure.retry import RetryPolicy  # noqa: E402


class TestFaultSchedule:
    def test_yaml_load_all_kinds(self):
        s = load_fault_schedule(
            """
seed: 42
events:
  - kill: a2
    at: 0.2
  - drop: "value_*"
    p: 0.5
  - delay: "*"
    p: 0.3
    seconds: 0.01
  - duplicate: "ping"
    count: 1
  - transport_error: "*"
    p: 0.1
  - reorder: "*"
    p: 0.2
    seconds: 0.02
  - device_fault: 2
"""
        )
        assert s.seed == 42
        assert s.kills == [KillEvent(agent="a2", at=0.2)]
        assert len(s.rules) == 5
        assert s.device_faults == 2

    def test_yaml_roundtrip_through_dict(self):
        s = FaultSchedule(
            seed=7,
            events=[
                KillEvent("a1", at=1.0),
                MessageRule(action="drop", pattern="m*", p=0.25),
                DeviceFault(count=3),
            ],
        )
        assert FaultSchedule.from_dict(s.to_dict()) == s

    def test_kill_process_event(self):
        # graftdur's crash model (make durability-smoke): abrupt
        # whole-process death at t — both spellings parse, and the event
        # round-trips through to_dict
        from pydcop_tpu.chaos import KillProcessEvent

        s = load_fault_schedule(
            "seed: 1\nevents:\n  - kill_process: true\n    at: 2.5\n"
        )
        assert s.process_kills == [KillProcessEvent(at=2.5)]
        assert s.process_kills[0].exit_code == 137
        assert not s.kills
        short = load_fault_schedule(
            "events:\n  - kill_process: 1.5\n"
        )
        assert short.process_kills == [KillProcessEvent(at=1.5)]
        s2 = FaultSchedule(
            seed=3, events=[KillProcessEvent(at=0.5, exit_code=9)]
        )
        assert FaultSchedule.from_dict(s2.to_dict()) == s2
        # a falsy value must NOT mean "kill at t=0" — a templated
        # schedule toggling the event off would nuke the process
        with pytest.raises(ValueError, match="kill_process"):
            load_fault_schedule("events:\n  - kill_process: false\n")
        with pytest.raises(ValueError, match="kill_process"):
            load_fault_schedule("events:\n  - kill_process:\n")

    def test_invalid_action_rejected(self):
        with pytest.raises(ValueError, match="invalid fault action"):
            MessageRule(action="explode", pattern="*")

    def test_invalid_probability_rejected(self):
        with pytest.raises(ValueError, match="outside"):
            MessageRule(action="drop", pattern="*", p=1.5)

    def test_unknown_event_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultSchedule.from_dict({"events": [{"frobnicate": "x"}]})

    def test_rule_matching(self):
        r = MessageRule(
            action="drop", pattern="value_*", dest="c2", src="c*"
        )
        assert r.matches("c1", "c2", "value_change")
        assert not r.matches("c1", "c2", "metrics")
        assert not r.matches("c1", "other", "value_change")
        assert not r.matches("x1", "c2", "value_change")


class TestDeterminism:
    """The chaos determinism contract: decisions are keyed hashes, so the
    canonical event log is bit-identical across runs and thread
    interleavings (docs/chaos.md)."""

    SCHEDULE = FaultSchedule(
        seed=99,
        events=[
            MessageRule(action="drop", pattern="algo", p=0.3),
            MessageRule(action="delay", pattern="*", p=0.4, seconds=0.0),
        ],
    )

    def test_unit_draw_is_stable_and_uniformish(self):
        a = unit_draw(1, "s", 0)
        assert a == unit_draw(1, "s", 0)  # pure
        assert 0.0 <= a < 1.0
        draws = [unit_draw(1, "s", n) for n in range(2000)]
        assert 0.4 < sum(draws) / len(draws) < 0.6
        # and keyed: any component changes the draw
        assert unit_draw(2, "s", 0) != a
        assert unit_draw(1, "t", 0) != a

    def _feed(self, controller, sends):
        for src, dest, mtype in sends:
            controller.on_send("ag1", "ag2", src, dest, mtype)

    def test_same_seed_same_log_bit_identical(self):
        sends = [
            ("c1", "c2", "algo"),
            ("c1", "c3", "mgt"),
            ("c2", "c1", "algo"),
        ] * 40
        c1, c2 = (
            ChaosController(self.SCHEDULE),
            ChaosController(self.SCHEDULE),
        )
        self._feed(c1, sends)
        self._feed(c2, sends)
        log1, log2 = c1.event_log(), c2.event_log()
        assert log1  # the schedule fires on this traffic
        assert log1 == log2

    def test_log_identical_across_thread_interleavings(self):
        # each worker owns one stream; the global interleaving is
        # randomized per run, the canonical log must not care
        streams = [
            [("w%d" % w, "c2", "algo")] * 50 for w in range(4)
        ]

        def run_threaded(seed):
            c = ChaosController(self.SCHEDULE)
            rng = random.Random(seed)

            def worker(sends, delay):
                for s in sends:
                    if delay:
                        time.sleep(0)
                    c.on_send("ag1", "ag2", *s)

            threads = [
                threading.Thread(
                    target=worker, args=(s, rng.random() < 0.5)
                )
                for s in streams
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return c.event_log()

        log_a = run_threaded(seed=1)
        log_b = run_threaded(seed=2)
        assert log_a
        assert log_a == log_b

    def test_different_seed_different_decisions(self):
        sends = [("c1", "c2", "algo")] * 50
        c1 = ChaosController(self.SCHEDULE)
        c2 = ChaosController(
            FaultSchedule(seed=100, events=self.SCHEDULE.events)
        )
        self._feed(c1, sends)
        self._feed(c2, sends)
        assert c1.event_log() != c2.event_log()

    def test_count_cap_limits_firings(self):
        c = ChaosController(
            FaultSchedule(
                seed=1,
                events=[
                    MessageRule(
                        action="duplicate", pattern="*", p=1.0, count=2
                    )
                ],
            )
        )
        dups = 0
        for _ in range(10):
            dups += c.on_send("a", "b", "c1", "c2", "m").duplicates
        assert dups == 2

    def test_device_faults_consumed_once_each(self):
        c = ChaosController(
            FaultSchedule(seed=0, events=[DeviceFault(count=2)])
        )
        assert [c.device_fault() for _ in range(4)] == [
            True, True, False, False,
        ]


class _Sink:
    def __init__(self):
        self.received = []


def _wrapped_pair(schedule):
    """a1 -> a2 with a chaos-wrapped sender layer; returns (m1, m2, ctl)."""
    ctl = ChaosController(schedule)
    inner1, l2 = InProcessCommunicationLayer(), InProcessCommunicationLayer()
    l1 = ChaosCommunicationLayer(inner1, ctl)
    m1, m2 = Messaging("a1", l1), Messaging("a2", l2)
    m2.register_computation("c2", _Sink())
    m1.register_route("c2", "a2", l2.address)
    return m1, m2, ctl


class TestChaosLayer:
    def test_drop_loses_message_silently(self):
        m1, m2, ctl = _wrapped_pair(
            FaultSchedule(
                seed=0,
                events=[MessageRule(action="drop", pattern="*", p=1.0)],
            )
        )
        m1.post_msg("c1", "c2", Message("m", 1))
        assert m2.next_msg(timeout=0.1) is None
        assert ctl.action_counts() == {"drop": 1}

    def test_duplicate_delivers_twice(self):
        m1, m2, _ = _wrapped_pair(
            FaultSchedule(
                seed=0,
                events=[
                    MessageRule(action="duplicate", pattern="*", p=1.0)
                ],
            )
        )
        m1.post_msg("c1", "c2", Message("m", "x"))
        got = [m2.next_msg(timeout=0.5), m2.next_msg(timeout=0.5)]
        assert [g[2].content for g in got] == ["x", "x"]

    def test_delay_sleeps_then_delivers(self):
        m1, m2, _ = _wrapped_pair(
            FaultSchedule(
                seed=0,
                events=[
                    MessageRule(
                        action="delay", pattern="*", p=1.0, seconds=0.1
                    )
                ],
            )
        )
        t0 = time.perf_counter()
        m1.post_msg("c1", "c2", Message("m", 1))
        elapsed = time.perf_counter() - t0
        assert elapsed >= 0.1
        assert m2.next_msg(timeout=0.5)[2].content == 1

    def test_transport_error_respects_on_error_contract(self):
        ctl = ChaosController(
            FaultSchedule(
                seed=0,
                events=[
                    MessageRule(
                        action="transport_error", pattern="*", p=1.0
                    )
                ],
            )
        )
        inner = InProcessCommunicationLayer(on_error="fail")
        layer = ChaosCommunicationLayer(inner, ctl)
        target = InProcessCommunicationLayer()
        Messaging("a2", target).register_computation("c2", _Sink())
        with pytest.raises(UnreachableAgent, match="chaos"):
            layer.send_msg(
                "a1", "a2", target, "c1", "c2", Message("m", 1), 20
            )
        # ignore mode: reported as a failed send, inner never invoked
        inner2 = InProcessCommunicationLayer(on_error="ignore")
        layer2 = ChaosCommunicationLayer(inner2, ctl)
        ok = layer2.send_msg(
            "a1", "a2", target, "c1", "c2", Message("m", 1), 20
        )
        assert ok is False

    def test_clean_decision_passes_through(self):
        m1, m2, ctl = _wrapped_pair(FaultSchedule(seed=0, events=[]))
        m1.post_msg("c1", "c2", Message("m", "thru"))
        assert m2.next_msg(timeout=0.5)[2].content == "thru"
        assert ctl.event_log() == []


class TestRetryPolicy:
    def test_backoff_is_capped_exponential(self):
        p = RetryPolicy(base_delay=0.1, max_delay=0.5, jitter="none")
        assert [p.backoff(a) for a in range(5)] == [
            0.1, 0.2, 0.4, 0.5, 0.5,
        ]
        assert p.sleep_duration(2) == 0.4

    def test_full_jitter_within_bounds_and_seeded(self):
        p1 = RetryPolicy(base_delay=0.2, max_delay=2.0, seed=5)
        p2 = RetryPolicy(base_delay=0.2, max_delay=2.0, seed=5)
        d1 = [p1.sleep_duration(a) for a in range(20)]
        d2 = [p2.sleep_duration(a) for a in range(20)]
        assert d1 == d2  # seeded: reproducible schedules
        for a, d in enumerate(d1):
            assert 0.0 <= d <= p1.backoff(a)

    def test_equal_jitter_bounded_below(self):
        p = RetryPolicy(base_delay=0.2, jitter="equal", seed=1)
        for a in range(10):
            assert p.backoff(a) / 2 <= p.sleep_duration(a) <= p.backoff(a)

    def test_attempt_cap(self):
        p = RetryPolicy(max_attempts=2, base_delay=0.0)
        started = p.start()
        assert p.sleep_before_retry(0, started) is True
        assert p.sleep_before_retry(1, started) is False

    def test_deadline_cap(self):
        p = RetryPolicy(
            max_attempts=10, base_delay=0.05, deadline=0.0, jitter="none"
        )
        # deadline already exhausted: no retry, and no sleep happened
        t0 = time.perf_counter()
        assert p.sleep_before_retry(0, p.start() - 1.0) is False
        assert time.perf_counter() - t0 < 0.05

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError):
            RetryPolicy(jitter="sometimes")


class TestBarrierDiagnostics:
    """PR 3 satellite: a missed replication barrier must name the agents
    that never acked, not raise a bare TimeoutError."""

    def _dcop(self):
        from pydcop_tpu.dcop import (
            DCOP,
            AgentDef,
            Domain,
            Variable,
            constraint_from_str,
        )

        d = Domain("colors", "", ["R", "G", "B"])
        x, y, z = Variable("x", d), Variable("y", d), Variable("z", d)
        dcop = DCOP("chain")
        dcop += constraint_from_str("c1", "10 if x == y else 0", [x, y])
        dcop += constraint_from_str("c2", "10 if y == z else 0", [y, z])
        dcop.add_agents(
            [AgentDef(f"a{i}", capacity=100) for i in range(3)]
        )
        return dcop

    def test_replication_timeout_names_stalled_agents(self):
        from pydcop_tpu.infrastructure.run import run_local_thread_dcop

        orchestrator = run_local_thread_dcop(
            "dsa", self._dcop(), "oneagent", n_cycles=5
        )
        try:
            orchestrator.deploy_computations()
            # crash one agent BEFORE replication: its ack never arrives.
            # The barrier timeout leaves room for the survivors' visit
            # timeouts — an owner visiting the corpse needs visit_timeout
            # seconds to treat the silence as a refusal and move on
            orchestrator._local_agents["a1"].crash()
            with pytest.raises(TimeoutError) as exc:
                orchestrator.start_replication(k=1, timeout=4.0)
            assert "a1" in str(exc.value)
            assert "a0" not in str(exc.value).split("acked:")[0]
        finally:
            orchestrator.stop_agents(timeout=2)
            orchestrator.stop()

    def test_degraded_mode_proceeds_past_replication_timeout(self):
        from pydcop_tpu.infrastructure.run import run_local_thread_dcop

        orchestrator = run_local_thread_dcop(
            "dsa",
            self._dcop(),
            "oneagent",
            n_cycles=5,
            chaos=ChaosController(FaultSchedule(seed=0, events=[])),
        )
        try:
            orchestrator.deploy_computations()
            orchestrator._local_agents["a1"].crash()
            # degrade_on_timeout (set by the chaos wiring): no raise,
            # the run proceeds on partial replication and still solves
            orchestrator.start_replication(k=1, timeout=1.5)
            orchestrator.run(timeout=30)
            assert orchestrator.status == "FINISHED"
            assignment, _ = orchestrator.current_solution()
            assert set(assignment) == {"x", "y", "z"}
        finally:
            orchestrator.stop_agents(timeout=2)
            orchestrator.stop()


class TestChaosVerb:
    """The ``pydcop_tpu chaos`` CLI verb, parsed and run in-process."""

    def _args(self, argv):
        import argparse

        from pydcop_tpu.commands import chaos as chaos_cmd

        parser = argparse.ArgumentParser()
        sub = parser.add_subparsers()
        chaos_cmd.set_parser(sub)
        return parser.parse_args(["chaos", *argv])

    def test_kill_and_repair_replay(self, tmp_path):
        sched = tmp_path / "sched.yaml"
        sched.write_text(
            """
seed: 5
events:
  - kill: a00001
    at: 0.1
  - delay: "*"
    p: 0.1
    seconds: 0.01
"""
        )
        out = tmp_path / "result.json"
        evlog = tmp_path / "events.json"
        args = self._args(
            [
                "-a", "dsa", "-n", "10", "--seed", "0", "-k", "1",
                "--fault-schedule", str(sched),
                "--event-log", str(evlog),
                "--max-dead-letters", "0",
                "--check-convergence",
                "/root/repo/tests/instances/graph_coloring.yaml",
            ]
        )
        args.output = str(out)
        from pydcop_tpu.commands.chaos import run_cmd

        rc = run_cmd(args, timeout=90)
        assert rc == 0
        import json

        result = json.loads(out.read_text())
        assert result["status"] == "FINISHED"
        assert result["chaos"]["converged"] is True
        assert result["chaos"]["dead_letters"] == 0
        kills = [
            e for e in result["chaos"]["events"] if e["action"] == "kill"
        ]
        assert kills and kills[0]["agent"] == "a00001"
        # the standalone event log matches the embedded one
        dumped = json.loads(evlog.read_text())
        assert dumped["events"] == result["chaos"]["events"]
