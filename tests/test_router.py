"""graftha: the HA serve router — tpu_part bucket-affinity placement (and
the measured queue-p99 win over round-robin), SLO-driven admission
(shed/defer/release with structured events + counters), failover under
worker death (exactly-once rescue, manifest adoption, resolve-from-
scratch accounting), retry-bounded forwards and batch-window tuning
(pydcop_tpu/serve/router.py, docs/serving.md "HA fleet").

Everything runs against a fake fleet with injectable fetch/post and a
fake clock — no sockets, no sleeps beyond the retry policy's own."""

import json

import pytest

from pydcop_tpu.infrastructure.retry import RetryPolicy
from pydcop_tpu.serve.router import PRIORITIES, Router, affinity_key
from pydcop_tpu.telemetry import telemetry_off
from pydcop_tpu.telemetry.federate import FleetTarget
from pydcop_tpu.telemetry.metrics import metrics_registry, percentile
from pydcop_tpu.telemetry.slo import parse_objective


@pytest.fixture(autouse=True)
def _metrics_on(tmp_path, monkeypatch):
    # burn-tripped engines dump a postmortem into the cwd by default;
    # keep test runs from touching the repo checkout
    monkeypatch.chdir(tmp_path)
    metrics_registry.enabled = True
    yield
    telemetry_off()


def _yaml_vars(n: int) -> str:
    """A parse-only DCOP yaml with n variables (the router never
    compiles it; the fake workers never solve it)."""
    rows = "\n".join(f"  v{i}: {{domain: d}}" for i in range(n))
    return f"variables:\n{rows}\nconstraints: {{}}\n"


def _spec(n_vars: int, seed: int = 0, **extra):
    spec = {
        "dcop_yaml": _yaml_vars(n_vars),
        "algo": "dsa",
        "n_cycles": 10,
        "seed": seed,
    }
    spec.update(extra)
    return spec


#: simulated queue latency: a bucket's FIRST solve on a worker pays the
#: cold executable compile, warm hits don't (the serve layer's actual
#: economics, scaled down)
COLD_MS = 300.0
WARM_MS = 2.0


class HAWorker:
    def __init__(self, name):
        self.name = name
        self.state = "serving"
        self.scrape_dead = False
        self.post_dead = False
        self.auto_finish = True
        self.tenants = {}
        self.compiled = set()
        self.queue_ms = []
        self.window_ms = None
        self.solves = 0
        self.post_count = {}


class HAFleet:
    """Injectable transport: fetch() is the scrape surface, post() the
    forward surface; per-worker kill switches for scrapes and posts
    separately (a worker can be scrape-alive but forward-dead)."""

    def __init__(self, names):
        self.workers = {n: HAWorker(n) for n in names}

    def targets(self):
        return [
            FleetTarget(n, f"http://ha/{n}")
            for n in sorted(self.workers)
        ]

    def _worker(self, url):
        name = url.split("/ha/", 1)[1].split("/", 1)[0]
        return self.workers[name]

    def finish(self, name, tid, cost=100.0):
        rec = self.workers[name].tenants[tid]
        rec["status"] = "done"
        rec["cost"] = cost

    def fetch(self, url):
        w = self._worker(url)
        if w.scrape_dead:
            return None
        if url.endswith("/metrics.json"):
            return {"time": 0.0, "metrics": {}}
        if url.endswith("/status"):
            return {
                "status": "serve",
                "state": w.state,
                "queue_depth": 0,
                "solves": w.solves,
            }
        if "/result/" in url:
            tid = url.rsplit("/", 1)[-1]
            rec = w.tenants.get(tid)
            # a real 404 comes back as a transport None (_http_fetch)
            return dict(rec) if rec is not None else None
        raise AssertionError(f"unexpected fetch {url}")

    def post(self, url, doc):
        w = self._worker(url)
        if w.post_dead:
            return None
        if url.endswith("/window"):
            w.window_ms = doc["window_ms"]
            return 200, {"window_ms": doc["window_ms"]}
        if url.endswith("/shutdown"):
            w.state = "draining"
            return 200, {"state": "draining"}
        assert url.endswith("/solve"), url
        if w.state != "serving":
            return 503, {
                "error": f"server is {w.state}",
                "state": w.state,
                "peers": [],
            }
        tid = doc["tenant"]
        w.post_count[tid] = w.post_count.get(tid, 0) + 1
        akey = affinity_key(doc)
        cold = akey not in w.compiled
        w.compiled.add(akey)
        w.queue_ms.append(COLD_MS if cold else WARM_MS)
        w.tenants[tid] = {
            "tenant": tid,
            "status": "running",
            "seed": doc.get("seed"),
        }
        if self.auto_done(w):
            self.finish(w.name, tid, cost=100.0 + float(doc.get("seed", 0)))
        w.solves += 1
        return 200, {"tenant": tid, "trace": doc.get("trace")}

    @staticmethod
    def auto_done(w):
        return w.auto_finish


def _router(fleet, clock, **kw):
    kw.setdefault("placement", "affinity")
    kw.setdefault("scrape_retry", None)
    kw.setdefault(
        "retry",
        RetryPolicy(
            max_attempts=2, base_delay=0.001, max_delay=0.002,
            jitter="none",
        ),
    )
    return Router(
        fleet.targets(),
        clock=clock,
        fetch=fleet.fetch,
        post=fleet.post,
        **kw,
    )


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# ---------------------------------------------------------------------------
# affinity keys
# ---------------------------------------------------------------------------


class TestAffinityKey:
    def test_same_pow2_class_shares_a_bucket(self):
        assert affinity_key(_spec(2)) == affinity_key(_spec(3))

    def test_distinct_pow2_classes_split(self):
        a = affinity_key(_spec(2))
        b = affinity_key(_spec(9))
        assert a != b
        assert a.startswith("dsa/") and b.startswith("dsa/")

    def test_algo_is_part_of_the_key(self):
        assert affinity_key(_spec(2)) != affinity_key(
            _spec(2, algo="mgm")
        )

    def test_unparseable_yaml_still_routes(self):
        key = affinity_key({"dcop_yaml": ":\n  - ][", "algo": "dsa"})
        assert key == "dsa/v0c0"


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------


class TestPlacement:
    def test_affinity_map_deterministic_and_live(self):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        maps = []
        for _ in range(2):
            r = _router(fleet, clock)
            r.tick(now=clock())
            for nv, seed in ((2, 0), (9, 1), (17, 2)):
                code, payload, _ = r.submit(_spec(nv, seed), now=clock())
                assert code == 200, payload
            maps.append(dict(r.status(now=clock())["placement"]["buckets"]))
        assert maps[0] == maps[1]
        assert set(maps[0].values()) <= {"w0", "w1"}
        assert len(maps[0]) == 3  # one placement per bucket

    def test_single_worker_takes_everything(self):
        clock = FakeClock()
        fleet = HAFleet(["only"])
        r = _router(fleet, clock)
        r.tick(now=clock())
        for nv in (2, 9, 17):
            code, payload, _ = r.submit(_spec(nv, nv), now=clock())
            assert code == 200 and payload["worker"] == "only"

    def test_draining_worker_excluded_from_placement(self):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        fleet.workers["w0"].state = "draining"
        r = _router(fleet, clock)
        r.tick(now=clock())
        for seed in range(4):
            code, payload, _ = r.submit(_spec(2, seed), now=clock())
            assert code == 200 and payload["worker"] == "w1", payload

    def test_affinity_beats_round_robin_on_queue_p99(self):
        """ISSUE tentpole evidence: a two-bucket skewed workload through
        affinity placement compiles each bucket ONCE fleet-wide, while
        round-robin compiles it once PER WORKER — with cold compiles
        dominating the queue tail, affinity's measured p99 stays warm
        and round-robin's lands on a cold hit."""
        p99 = {}
        cold = {}
        for strategy in ("affinity", "round_robin"):
            clock = FakeClock()
            fleet = HAFleet(["w0", "w1"])
            r = _router(fleet, clock, placement=strategy)
            r.tick(now=clock())
            # two buckets (v-class 4 and 16), paired head so round-robin
            # provably sprays both buckets across both workers
            seq = [2, 2, 9, 9] + [2 if i % 2 else 9 for i in range(296)]
            for i, nv in enumerate(seq):
                code, payload, _ = r.submit(
                    _spec(nv, seed=i), now=clock()
                )
                assert code == 200, payload
            samples = sorted(
                ms
                for w in fleet.workers.values()
                for ms in w.queue_ms
            )
            assert len(samples) == 300
            p99[strategy] = percentile(samples, 0.99)
            cold[strategy] = sum(1 for s in samples if s == COLD_MS)
        # affinity: one compile per bucket fleet-wide; rr: one per
        # (bucket, worker) pair
        assert cold["affinity"] <= 3
        assert cold["round_robin"] == 4
        assert p99["affinity"] < p99["round_robin"], (p99, cold)
        assert p99["round_robin"] == COLD_MS


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def _burning_router(fleet, clock, **kw):
    """A router whose local forward objective is already fast-burning:
    the availability objective saw bad forwards, so alerts_active()
    carries a fast alert when evaluate() runs."""
    r = _router(
        fleet,
        clock,
        router_objectives=[parse_objective("fwd=availability>=99%@300s")],
        **kw,
    )
    r.tick(now=clock())
    for i in range(20):
        r.engine.record_request(f"warm{i}", "failed", 0.01)
    clock.advance(1.0)
    r.engine.evaluate(clock())
    assert r.engine.alerts_active(), "availability objective must burn"
    assert r.admission_mode() == "shedding"
    return r


class TestAdmission:
    def test_priorities_validated(self):
        clock = FakeClock()
        fleet = HAFleet(["w0"])
        r = _router(fleet, clock)
        code, payload, _ = r.submit(
            _spec(2, priority="urgent"), now=clock()
        )
        assert code == 400 and "priority" in payload["error"]
        assert set(PRIORITIES) == {"high", "normal", "low"}

    def test_shed_low_defer_normal_admit_high_under_burn(self):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        r = _burning_router(fleet, clock)
        shed0 = metrics_registry.counter("router.shed_total", "").value(
            reason="fast-burn", priority="low"
        )
        code, payload, headers = r.submit(
            _spec(2, priority="low"), now=clock()
        )
        assert code == 503
        assert payload["shed"] is True and payload["reason"] == "fast-burn"
        assert payload["alerts"]
        assert headers and "Retry-After" in headers
        assert payload["peers"]  # live peers: fail over without guessing
        assert (
            metrics_registry.counter("router.shed_total", "").value(
                reason="fast-burn", priority="low"
            )
            == shed0 + 1
        )
        code, payload, _ = r.submit(
            _spec(2, seed=1, priority="normal"), now=clock()
        )
        assert code == 202 and payload["deferred"] is True
        code, payload, _ = r.submit(
            _spec(2, seed=2, priority="high"), now=clock()
        )
        assert code == 200 and payload["worker"] in ("w0", "w1")
        st = r.status(now=clock())
        assert st["admission"]["mode"] == "shedding"
        assert st["admission"]["shed"] == 1
        assert st["admission"]["deferred"] == 1
        kinds = [e["event"] for e in st["events"]]
        assert "shed" in kinds and "defer" in kinds

    def test_deferred_released_when_burn_clears(self):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        r = _burning_router(fleet, clock)
        code, payload, _ = r.submit(
            _spec(2, priority="normal", tenant="parked"), now=clock()
        )
        assert code == 202
        # good traffic + time: the fast windows drain, the fast alert
        # resolves and admission reopens (the slow-burn alert rightly
        # lingers — only fast burn gates admission)
        for step in range(8):
            clock.advance(1.0)
            for i in range(10):
                r.engine.record_request(f"ok{step}-{i}", "done", 0.01)
            r.tick(now=clock())
            if r.admission_mode() == "open":
                break
        assert r.admission_mode() == "open"
        rec = r.result("parked")
        assert rec["status"] == "done"
        assert r.status(now=clock())["admission"]["released"] >= 1

    def test_normal_defer_bounded_by_defer_max(self):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        r = _burning_router(fleet, clock, defer_max_s=5.0)
        code, _, _ = r.submit(
            _spec(2, priority="normal", tenant="slowpoke"), now=clock()
        )
        assert code == 202
        # keep the burn alive: deferral must still end at defer_max_s
        for _ in range(7):
            clock.advance(1.0)
            for i in range(3):
                r.engine.record_request(f"b{clock()}{i}", "failed", 0.01)
            r.tick(now=clock())
        assert r.result("slowpoke")["status"] == "done"

    def test_no_live_worker_defers_instead_of_failing(self):
        clock = FakeClock()
        fleet = HAFleet(["w0"])
        fleet.workers["w0"].scrape_dead = True
        fleet.workers["w0"].post_dead = True
        r = _router(fleet, clock)
        r.tick(now=clock())
        code, payload, _ = r.submit(_spec(2, tenant="waiting"), now=clock())
        assert code == 202 and payload["reason"] == "no-worker"
        # worker comes back: the control loop flushes the parked tenant
        fleet.workers["w0"].scrape_dead = False
        fleet.workers["w0"].post_dead = False
        clock.advance(1.0)
        r.tick(now=clock())
        assert r.result("waiting")["status"] == "done"


# ---------------------------------------------------------------------------
# failover
# ---------------------------------------------------------------------------


def _single_bucket_router(fleet, clock, **kw):
    r = _router(fleet, clock, **kw)
    r.tick(now=clock())
    return r


def _owner_of(r, tid):
    return r.result(tid)["owner" if "owner" in r.result(tid) else "worker"]


class TestFailover:
    def test_victims_resumed_exactly_once_on_survivors(self):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        for w in fleet.workers.values():
            w.auto_finish = False  # tenants stay in flight
        r = _single_bucket_router(fleet, clock)
        tids = []
        for i in range(3):
            code, payload, _ = r.submit(
                _spec(2, seed=i, tenant=f"t{i}"), now=clock()
            )
            assert code == 200
            tids.append(payload["tenant"])
        victim = r.result(tids[0])["worker"]
        survivor = "w1" if victim == "w0" else "w0"
        assert all(r.result(t)["worker"] == victim for t in tids)
        fleet.workers[victim].scrape_dead = True
        fleet.workers[victim].post_dead = True
        scratch0 = metrics_registry.counter(
            "router.resolve_from_scratch", ""
        ).value()
        clock.advance(1.0)
        r.tick(now=clock())  # worker_up flips -> failover
        for tid in tids:
            rec = r.result(tid)
            assert rec["status"] in ("running", "forwarded", "done"), rec
            assert rec["worker"] == survivor
            # exactly once on the survivor, exactly once on the victim
            assert fleet.workers[survivor].post_count[tid] == 1
            assert fleet.workers[victim].post_count[tid] == 1
        assert (
            metrics_registry.counter(
                "router.resolve_from_scratch", ""
            ).value()
            == scratch0 + 3
        )
        st = r.status(now=clock())
        assert st["admission"]["failovers"] == 1
        assert st["admission"]["from_scratch"] == 3
        kinds = [e["event"] for e in st["events"]]
        assert "failover" in kinds and "resolve-from-scratch" not in kinds

    def test_terminal_tenants_not_rerun(self):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        for w in fleet.workers.values():
            w.auto_finish = False
        r = _single_bucket_router(fleet, clock)
        _, done_p, _ = r.submit(_spec(2, tenant="fin"), now=clock())
        _, live_p, _ = r.submit(
            _spec(2, seed=1, tenant="wip"), now=clock()
        )
        victim = done_p["worker"]
        assert live_p["worker"] == victim
        survivor = "w1" if victim == "w0" else "w0"
        fleet.finish(victim, "fin", cost=123.0)
        clock.advance(1.0)
        r.tick(now=clock())  # result poll caches the terminal record
        assert r.result("fin")["status"] == "done"
        fleet.workers[victim].scrape_dead = True
        fleet.workers[victim].post_dead = True
        clock.advance(1.0)
        r.tick(now=clock())
        # the finished tenant is NEVER re-posted anywhere
        assert "fin" not in fleet.workers[survivor].post_count
        rec = r.result("fin")
        assert rec["status"] == "done" and rec["cost"] == 123.0
        # the in-flight one moved
        assert r.result("wip")["worker"] == survivor

    def test_manifest_adoption_transfers_ownership(self, tmp_path):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        for w in fleet.workers.values():
            w.auto_finish = False
        state = tmp_path / "state"
        r = _single_bucket_router(fleet, clock, state_dir=str(state))
        _, payload, _ = r.submit(_spec(2, tenant="ckpt"), now=clock())
        victim = payload["worker"]
        survivor = "w1" if victim == "w0" else "w0"
        # the victim's graftdur manifest holds the terminal result
        vdir = state / victim
        vdir.mkdir(parents=True)
        (vdir / "fleet-manifest.json").write_text(
            json.dumps(
                {
                    "format": "graftdur-v1",
                    "kind": "fleet",
                    "endpoint": f"http://ha/{victim}",
                    "wrote_unix_s": 1.0,
                    "tenants": {
                        "ckpt": {
                            "status": "done",
                            "cost": 42.0,
                            "assignment": {"v0": 1},
                        }
                    },
                }
            )
        )
        adopted0 = metrics_registry.counter(
            "router.adopted_results", ""
        ).value()
        fleet.workers[victim].scrape_dead = True
        fleet.workers[victim].post_dead = True
        clock.advance(1.0)
        r.tick(now=clock())
        rec = r.result("ckpt")
        # adopted, never re-solved: ownership transfer recorded
        assert rec["status"] == "done"
        assert rec["cost"] == 42.0
        assert rec["result_source"] == "manifest"
        assert rec["owner"] == victim
        assert "ckpt" not in fleet.workers[survivor].post_count
        assert (
            metrics_registry.counter(
                "router.adopted_results", ""
            ).value()
            == adopted0 + 1
        )
        assert any(
            h["event"] == "adopt" for h in rec["history"]
        )
        # the router's own ownership manifest records the transfer
        doc = json.loads(
            (state / "router-manifest.json").read_text()
        )
        assert doc["kind"] == "router"
        assert doc["tenants"]["ckpt"]["status"] == "done"

    def test_failed_forward_triggers_failover_without_scrape_flip(self):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        for w in fleet.workers.values():
            w.auto_finish = False
        r = _single_bucket_router(fleet, clock)
        _, p0, _ = r.submit(_spec(2, tenant="first"), now=clock())
        victim = p0["worker"]
        survivor = "w1" if victim == "w0" else "w0"
        # the victim dies for FORWARDS only — scrapes still answer
        fleet.workers[victim].post_dead = True
        code, p1, _ = r.submit(
            _spec(2, seed=1, tenant="second"), now=clock()
        )
        # the failed forward marks the victim suspect, rescues 'first'
        # and both tenants land on the survivor
        assert code == 200 and p1["worker"] == survivor
        assert r.result("first")["worker"] == survivor
        assert fleet.workers[survivor].post_count["first"] == 1

    def test_flap_recovers_after_scrape_comes_back(self):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        r = _single_bucket_router(fleet, clock)
        for w in fleet.workers.values():
            w.post_dead = True  # forwards fail fleet-wide, scrapes live
        code, _, _ = r.submit(_spec(2, tenant="a"), now=clock())
        assert code == 202  # every worker suspect -> parked, not lost
        assert r._suspect == {"w0", "w1"}
        for w in fleet.workers.values():
            w.post_dead = False
        clock.advance(1.0)
        r.tick(now=clock())  # the scrape refutes both suspicions
        assert not r._suspect
        assert r._live_workers(now=clock()) == ["w0", "w1"]
        # ...and the parked tenant was flushed to a worker
        assert r.result("a")["status"] in ("running", "done")


# ---------------------------------------------------------------------------
# forwards, deadlines, windows, drain
# ---------------------------------------------------------------------------


class TestControlLoop:
    def test_deadline_expires_unplaceable_tenant(self):
        clock = FakeClock()
        fleet = HAFleet(["w0"])
        fleet.workers["w0"].scrape_dead = True
        fleet.workers["w0"].post_dead = True
        r = _router(fleet, clock, tenant_deadline_s=10.0)
        r.tick(now=clock())
        code, _, _ = r.submit(_spec(2, tenant="doomed"), now=clock())
        assert code == 202
        clock.advance(11.0)
        r.tick(now=clock())
        rec = r.result("doomed")
        assert rec["status"] == "failed"
        assert rec["error"] == "deadline exceeded"
        assert (
            r.status(now=clock())["admission"]["deadline_expired"] == 1
        )

    def test_windows_widen_on_idle_and_narrow_on_load(self):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        r = _router(
            fleet, clock, window_base_ms=25.0, idle_ticks_to_widen=2
        )
        for _ in range(5):
            clock.advance(1.0)
            r.tick(now=clock())
        assert r.status(now=clock())["window"]["factor"] > 1.0
        assert fleet.workers["w0"].window_ms > 25.0
        # queues build: narrow straight back to base
        def busy_fetch(url):
            doc = fleet.fetch(url)
            if doc and url.endswith("/status"):
                doc["queue_depth"] = 5
            return doc

        r._fetch = busy_fetch
        r.collector._fetch = busy_fetch
        clock.advance(1.0)
        r.tick(now=clock())
        assert r.status(now=clock())["window"]["factor"] == 1.0
        assert fleet.workers["w0"].window_ms == 25.0
        adj = metrics_registry.counter(
            "router.window_adjust_total", ""
        )
        assert adj.value(direction="widen") >= 1
        assert adj.value(direction="narrow") >= 1

    def test_drain_rejects_with_structured_503_and_writes_manifest(
        self, tmp_path
    ):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        state = tmp_path / "state"
        r = _router(fleet, clock, state_dir=str(state))
        r.tick(now=clock())
        r.submit(_spec(2, tenant="before"), now=clock())
        assert r.drain(timeout=5.0)
        code, payload, headers = r.submit(_spec(2), now=clock())
        assert code == 503
        assert "Retry-After" in headers
        assert "peers" in payload
        doc = json.loads((state / "router-manifest.json").read_text())
        assert doc["state"] == "drained"
        assert doc["tenants"]["before"]["status"] == "done"

    def test_snapshot_merges_router_series_as_worker_router(self):
        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        r = _router(fleet, clock)
        r.tick(now=clock())
        r.submit(_spec(2), now=clock())
        snap = r.snapshot(now=clock())
        fwd = snap["metrics"]["router.forwards_total"]
        assert fwd["kind"] == "counter"
        assert all(
            e["labels"]["worker"] == "router" for e in fwd["values"]
        )
        # the fleet meta-series are there too
        assert "fleet.worker_up" in snap["metrics"]

    def test_http_surface_end_to_end(self):
        import urllib.error
        import urllib.request

        clock = FakeClock()
        fleet = HAFleet(["w0", "w1"])
        r = _router(
            fleet,
            clock,
            port=0,
            router_objectives=[
                parse_objective("fwd=availability>=99%@300s")
            ],
        )
        base = f"http://127.0.0.1:{r.http.port}"
        try:
            r.tick(now=clock())
            body = json.dumps(_spec(2, tenant="web")).encode()
            req = urllib.request.Request(
                base + "/solve", data=body, method="POST"
            )
            ans = json.loads(
                urllib.request.urlopen(req, timeout=10).read()
            )
            assert ans["tenant"] == "web"
            rec = json.loads(
                urllib.request.urlopen(
                    base + "/result/web", timeout=10
                ).read()
            )
            assert rec["status"] in ("forwarded", "done")
            st = json.loads(
                urllib.request.urlopen(
                    base + "/status", timeout=10
                ).read()
            )
            assert st["status"] == "router"
            assert st["admission"]["mode"] == "open"
            hz = json.loads(
                urllib.request.urlopen(
                    base + "/healthz", timeout=10
                ).read()
            )
            assert hz["state"] == "serving"
            slo = json.loads(
                urllib.request.urlopen(base + "/slo", timeout=10).read()
            )
            assert "objectives" in slo
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    base + "/result/nope", timeout=10
                )
            assert exc.value.code == 404
        finally:
            r.shutdown(drain=True)
        # drained router answers healthz 503
        assert r._http_healthz("/healthz", b"")[0] == 503


class TestPlacingClaim:
    """The submit thread places its tenant synchronously; the control
    loop's flush must never race that window (it double-POSTs the same
    tenant, and on a real worker the duplicate lands in the same batch
    window and forces a fresh vmap-capacity compile)."""

    def test_flush_skips_tenant_mid_placement(self):
        fleet = HAFleet(["w0"])
        clock = FakeClock()
        r = _router(fleet, clock)
        r.tick()
        raced = []
        real_post = fleet.post

        def post(url, body):
            # the tick thread firing exactly between the record insert
            # and the submit thread's own forward attempt
            if url.endswith("/solve") and not raced:
                raced.append(True)
                r._flush_deferred(clock())
            return real_post(url, body)

        r._post = post
        code, ans, _ = r.submit(_spec(8, tenant="raced"), now=clock())
        assert code == 200
        assert raced, "forward never reached the transport"
        assert fleet.workers["w0"].post_count["raced"] == 1
        assert r.status()["admission"]["released"] == 0

    def test_claim_cleared_after_placement(self):
        fleet = HAFleet(["w0"])
        clock = FakeClock()
        r = _router(fleet, clock)
        r.tick()
        code, ans, _ = r.submit(_spec(8, tenant="ok"), now=clock())
        assert code == 200
        assert r._tenants["ok"]["placing"] is False
        # a genuinely parked tenant (forward-dead fleet) is released by
        # the flush once a worker comes back: the claim must not stick
        fleet.workers["w0"].post_dead = True
        code, ans, _ = r.submit(_spec(8, tenant="parked"), now=clock())
        assert code == 202
        assert r._tenants["parked"]["placing"] is False
        fleet.workers["w0"].post_dead = False
        clock.advance(1.0)
        r.tick()
        assert r._tenants["parked"]["status"] == "forwarded"
