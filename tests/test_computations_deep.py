"""MessagePassingComputation depth tests, modeled on the reference's
coverage (/root/reference/tests/unit/test_infra_computations.py, ~506
LoC): periodic actions driven by a real agent loop (cadence, removal,
several periods, paused), handler registration semantics, and pause
buffering in both directions."""

import time

import pytest

pytest.importorskip("jax")

from pydcop_tpu.infrastructure.agents import Agent  # noqa: E402
from pydcop_tpu.infrastructure.communication import (  # noqa: E402
    InProcessCommunicationLayer,
)
from pydcop_tpu.infrastructure.computations import (  # noqa: E402
    ComputationException,
    Message,
    MessagePassingComputation,
    register,
)


def _wait(predicate, timeout=3.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


class _Probe(MessagePassingComputation):
    def __init__(self, name="probe"):
        super().__init__(name)
        self.pings = []

    @register("ping")
    def _on_ping(self, sender, msg, t):
        self.pings.append(msg.content)


@pytest.fixture()
def hosted():
    agent = Agent("host", InProcessCommunicationLayer())
    comp = _Probe()
    agent.add_computation(comp, publish=False)
    agent.start()
    comp.start()
    yield agent, comp
    agent.clean_shutdown()
    agent.join()


class TestPeriodicActions:
    def test_fires_repeatedly_at_period(self, hosted):
        agent, comp = hosted
        ticks = []
        comp.add_periodic_action(0.05, lambda: ticks.append(time.time()))
        assert _wait(lambda: len(ticks) >= 4)
        # cadence sanity: not all at once
        assert ticks[-1] - ticks[0] >= 0.1

    def test_remove_stops_firing(self, hosted):
        agent, comp = hosted
        ticks = []
        cb = comp.add_periodic_action(0.05, lambda: ticks.append(1))
        assert _wait(lambda: len(ticks) >= 2)
        comp.remove_periodic_action(cb)
        n = len(ticks)
        time.sleep(0.2)
        assert len(ticks) == n

    def test_several_periods_fire_proportionally(self, hosted):
        agent, comp = hosted
        fast, slow = [], []
        comp.add_periodic_action(0.03, lambda: fast.append(1))
        comp.add_periodic_action(0.15, lambda: slow.append(1))
        assert _wait(lambda: len(slow) >= 2, timeout=4)
        assert len(fast) > len(slow)

    def test_not_called_while_paused(self, hosted):
        agent, comp = hosted
        ticks = []
        comp.add_periodic_action(0.03, lambda: ticks.append(1))
        assert _wait(lambda: len(ticks) >= 1)
        comp.pause(True)
        time.sleep(0.1)  # let in-flight ticks settle
        n = len(ticks)
        time.sleep(0.2)
        assert len(ticks) <= n + 1  # at most one straggler
        comp.pause(False)
        assert _wait(lambda: len(ticks) > n + 1)


class TestHandlers:
    def test_unknown_message_type_raises(self):
        comp = _Probe()
        comp.start()
        with pytest.raises(ComputationException, match="no handler"):
            comp.on_message("s", Message("nope", 1), 0.0)

    def test_post_without_host_raises(self):
        comp = _Probe()
        comp.start()
        with pytest.raises(ComputationException, match="not hosted"):
            comp.post_msg("other", Message("ping", 1))

    def test_pause_buffers_in_and_out(self, hosted):
        agent, comp = hosted
        other = _Probe("other")
        agent.add_computation(other, publish=False)
        other.start()
        comp.pause(True)
        # inbound buffered
        comp.on_message("x", Message("ping", "in"), 0.0)
        assert comp.pings == []
        # outbound buffered
        comp.post_msg("other", Message("ping", "out"))
        time.sleep(0.1)
        assert other.pings == []
        comp.pause(False)
        assert comp.pings == ["in"]
        assert _wait(lambda: other.pings == ["out"])

    def test_message_delivery_through_agent(self, hosted):
        agent, comp = hosted
        other = _Probe("other")
        agent.add_computation(other, publish=False)
        other.start()
        comp.post_msg("other", Message("ping", 7))
        assert _wait(lambda: other.pings == [7])


class TestStatsTracing:
    """The per-step CSV trace (infrastructure/stats.py, reference
    stats.py:47-103): dormant by default, and once a stats file is set
    every handled message writes one schema row."""

    def test_disabled_by_default_writes_nothing(self, tmp_path):
        from pydcop_tpu.infrastructure import stats

        assert not stats.stats_enabled()
        # no file set: tracing is a no-op, not an error
        stats.trace_computation("c", 0, 0.001)

    def test_rows_written_per_handled_message(self, tmp_path):
        from pydcop_tpu.infrastructure import stats

        out = tmp_path / "trace.csv"
        stats.set_stats_file(str(out))
        try:
            comp = _Probe()
            comp.start()
            comp.on_message("peer", Message("ping", 1), 0.0)
            comp.on_message("peer", Message("ping", 2), 0.0)
        finally:
            stats.set_stats_file(None)
        lines = out.read_text().strip().splitlines()
        assert lines[0] == ",".join(stats.columns)
        assert len(lines) == 3  # header + one row per message
        row = lines[1].split(",")
        assert row[1] == "probe"
        assert float(row[3]) >= 0.0  # duration
        assert row[4] == "1"  # msg_count
        assert not stats.stats_enabled()


class TestStopSemantics:
    """stop() vs clean_shutdown() (reference agents.py:431 vs :445): the
    hard stop abandons the queue after the in-flight message; the clean
    one drains pending messages first."""

    @staticmethod
    def _agent_with_probe():
        agent = Agent("drain", InProcessCommunicationLayer())
        comp = _Probe()
        agent.add_computation(comp, publish=False)
        comp.start()
        return agent, comp

    def test_clean_shutdown_drains_pending(self):
        agent, comp = self._agent_with_probe()
        # enqueue a burst BEFORE the loop starts, then shut down cleanly:
        # every message must still be handled
        for i in range(50):
            agent.messaging.post_msg(
                "x", "probe", Message("ping", i), prio=20
            )
        agent.start()
        agent.clean_shutdown()
        agent.join(10.0)
        assert len(comp.pings) == 50

    def test_hard_stop_abandons_queue(self):
        # deterministic: the first message parks on an event while the
        # main thread issues the hard stop, so exactly the in-flight
        # message is handled and the rest of the queue is abandoned
        import threading

        gate = threading.Event()
        entered = threading.Event()

        class _Gated(_Probe):
            @register("ping")
            def _on_ping(self, sender, msg, t):
                entered.set()
                gate.wait(10.0)
                self.pings.append(msg.content)

        agent = Agent("drain2", InProcessCommunicationLayer())
        comp = _Gated("probe")
        agent.add_computation(comp, publish=False)
        comp.start()
        for i in range(50):
            agent.messaging.post_msg(
                "x", "probe", Message("ping", i), prio=20
            )
        agent.start()
        assert entered.wait(5.0)
        agent.stop()  # hard: exits after the in-flight message
        gate.set()
        agent.join(10.0)
        assert len(comp.pings) == 1
