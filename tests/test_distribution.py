"""Distribution-method tests (reference: tests/unit/test_distribution_*.py)."""

import pytest

from pydcop_tpu.computations_graph import constraints_hypergraph as chg
from pydcop_tpu.computations_graph import factor_graph as fg
from pydcop_tpu.dcop import (
    DCOP,
    AgentDef,
    Domain,
    Variable,
    constraint_from_str,
    load_dcop_from_file,
)
from pydcop_tpu.distribution import (
    Distribution,
    DistributionHints,
    ImpossibleDistributionException,
    load_distribution_module,
)
from pydcop_tpu.distribution._costs import distribution_cost
from pydcop_tpu.distribution.yamlformat import load_dist, yaml_dist

REF = "/root/reference/tests/instances"


def three_var_dcop():
    d = Domain("c", "", ["R", "G"])
    x, y, z = Variable("x", d), Variable("y", d), Variable("z", d)
    dcop = DCOP("t")
    dcop += constraint_from_str("c1", "1 if x == y else 0", [x, y])
    dcop += constraint_from_str("c2", "1 if y == z else 0", [y, z])
    dcop.add_agents(
        [AgentDef(f"a{i}", capacity=100) for i in range(1, 6)]
    )
    return dcop


class TestDistributionObjects:
    def test_mapping_and_reverse(self):
        d = Distribution({"a1": ["c1", "c2"], "a2": ["c3"]})
        assert d.agent_for("c3") == "a2"
        assert sorted(d.computations_hosted("a1")) == ["c1", "c2"]
        assert d.is_hosted(["c1", "c3"])

    def test_duplicate_hosting_rejected(self):
        with pytest.raises(ValueError):
            Distribution({"a1": ["c1"], "a2": ["c1"]})

    def test_host_on_agent_moves(self):
        d = Distribution({"a1": ["c1"], "a2": []})
        d.host_on_agent("a2", ["c1"])
        assert d.agent_for("c1") == "a2"
        assert d.computations_hosted("a1") == []

    def test_remove_agent_orphans(self):
        d = Distribution({"a1": ["c1", "c2"], "a2": ["c3"]})
        orphans = d.remove_agent("a1")
        assert sorted(orphans) == ["c1", "c2"]
        assert not d.has_computation("c1")

    def test_yaml_roundtrip(self):
        d = Distribution({"a1": ["c1"], "a2": ["c2", "c3"]})
        assert load_dist(yaml_dist(d)) == d


class TestOneAgent:
    def test_one_comp_per_agent(self):
        dcop = three_var_dcop()
        cg = chg.build_computation_graph(dcop)
        mod = load_distribution_module("oneagent")
        dist = mod.distribute(cg, dcop.agents.values())
        for a in dist.agents:
            assert len(dist.computations_hosted(a)) <= 1
        assert sorted(dist.computations) == ["x", "y", "z"]

    def test_not_enough_agents(self):
        dcop = three_var_dcop()
        cg = chg.build_computation_graph(dcop)
        mod = load_distribution_module("oneagent")
        with pytest.raises(ImpossibleDistributionException):
            mod.distribute(cg, [AgentDef("a1")])


class TestAdhoc:
    def test_must_host_respected(self):
        dcop = three_var_dcop()
        cg = chg.build_computation_graph(dcop)
        mod = load_distribution_module("adhoc")
        hints = DistributionHints(must_host={"a1": ["x"], "a2": ["y"]})
        dist = mod.distribute(cg, dcop.agents.values(), hints)
        assert dist.agent_for("x") == "a1"
        assert dist.agent_for("y") == "a2"

    def test_host_with_colocates(self):
        dcop = three_var_dcop()
        cg = chg.build_computation_graph(dcop)
        mod = load_distribution_module("adhoc")
        hints = DistributionHints(host_with={"x": ["z"]})
        dist = mod.distribute(cg, dcop.agents.values(), hints)
        assert dist.agent_for("x") == dist.agent_for("z")

    def test_capacity_respected(self):
        dcop = three_var_dcop()
        cg = chg.build_computation_graph(dcop)
        mod = load_distribution_module("adhoc")
        agents = [AgentDef("a1", capacity=1), AgentDef("a2", capacity=1000)]
        dist = mod.distribute(
            cg, agents, computation_memory=lambda n: 10.0
        )
        assert dist.computations_hosted("a1") == []

    def test_distribute_remove(self):
        dcop = three_var_dcop()
        cg = chg.build_computation_graph(dcop)
        mod = load_distribution_module("adhoc")
        dist = mod.distribute(cg, dcop.agents.values())
        agents = list(dcop.agents.values())
        hosting = dist.agent_for("x")
        new_dist = mod.distribute_remove([hosting], dist, cg, agents)
        assert new_dist.has_computation("x")
        assert new_dist.agent_for("x") != hosting


class TestGreedyAndIlp:
    @pytest.mark.parametrize(
        "method",
        [
            # the FULL registry (reference: one module per method under
            # pydcop/distribution/): greedy, ILP, computation-memory and
            # SECP families all place every computation of the instance
            "gh_cgdp", "heur_comhost", "oilp_cgdp", "ilp_fgdp",
            "ilp_compref", "ilp_compref_fg",
            "oilp_secp_cgdp", "oilp_secp_fgdp",
            "gh_secp_cgdp", "gh_secp_fgdp",
        ],
    )
    def test_distributes_reference_instance(self, method):
        dcop = load_dcop_from_file(f"{REF}/graph_coloring1.yaml")
        cg = fg.build_computation_graph(dcop)
        mod = load_distribution_module(method)
        from pydcop_tpu.algorithms import maxsum

        dist = mod.distribute(
            cg,
            dcop.agents.values(),
            computation_memory=maxsum.computation_memory,
            communication_load=maxsum.communication_load,
        )
        assert sorted(dist.computations) == sorted(
            n.name for n in cg.nodes
        )

    def test_ilp_beats_or_matches_greedy(self):
        dcop = load_dcop_from_file(f"{REF}/graph_coloring1.yaml")
        cg = fg.build_computation_graph(dcop)
        from pydcop_tpu.algorithms import maxsum

        agents = list(dcop.agents.values())
        greedy = load_distribution_module("gh_cgdp").distribute(
            cg,
            agents,
            computation_memory=maxsum.computation_memory,
            communication_load=maxsum.communication_load,
        )
        ilp = load_distribution_module("oilp_cgdp").distribute(
            cg,
            agents,
            computation_memory=maxsum.computation_memory,
            communication_load=maxsum.communication_load,
        )
        gc, _, _ = distribution_cost(
            greedy, cg, agents,
            communication_load=maxsum.communication_load,
        )
        ic, _, _ = distribution_cost(
            ilp, cg, agents,
            communication_load=maxsum.communication_load,
        )
        assert ic <= gc + 1e-9


class TestIlpFgdpHints:
    """ILP factor-graph distribution under hints and capacity, modeled on
    the reference's coverage (test_distribution_ilp_fgdp.py:69-280)."""

    def _setup(self):
        dcop = three_var_dcop()
        graph = fg.build_computation_graph(dcop)
        mod = load_distribution_module("ilp_fgdp")
        mem = lambda node: 10.0  # noqa: E731
        load = lambda node, target: 1.0  # noqa: E731
        return dcop, graph, mod, mem, load

    def _dist(self, hints=None, agents=None):
        dcop, graph, mod, mem, load = self._setup()
        return mod.distribute(
            graph,
            agents if agents is not None else dcop.agents.values(),
            hints=hints,
            computation_memory=mem,
            communication_load=load,
        )

    def test_respect_must_host_for_var(self):
        d = self._dist(DistributionHints(must_host={"a1": ["x"]}))
        assert d.agent_for("x") == "a1"

    def test_respect_must_host_for_factor(self):
        d = self._dist(DistributionHints(must_host={"a2": ["c1"]}))
        assert d.agent_for("c1") == "a2"

    def test_respect_must_host_var_and_factor_distinct_agents(self):
        d = self._dist(
            DistributionHints(must_host={"a1": ["x"], "a2": ["c1"]})
        )
        assert d.agent_for("x") == "a1"
        assert d.agent_for("c1") == "a2"

    def test_respect_must_host_same_agent(self):
        d = self._dist(DistributionHints(must_host={"a3": ["x", "c1"]}))
        assert d.agent_for("x") == "a3"
        assert d.agent_for("c1") == "a3"

    def test_all_computations_fixed(self):
        pins = {
            "a1": ["x"], "a2": ["y"], "a3": ["z"],
            "a4": ["c1"], "a5": ["c2"],
        }
        d = self._dist(DistributionHints(must_host=pins))
        for agent, comps in pins.items():
            for c in comps:
                assert d.agent_for(c) == agent

    def test_capacity_infeasible_raises(self):
        dcop, graph, mod, mem, load = self._setup()
        tiny = [AgentDef("a1", capacity=10)]  # 5 comps x 10 > 10
        with pytest.raises(ImpossibleDistributionException):
            mod.distribute(
                graph, tiny, computation_memory=mem,
                communication_load=load,
            )

    def test_communication_is_minimized(self):
        # with ample capacity on one agent the pure-communication ILP puts
        # EVERYTHING together: zero inter-agent traffic beats any split
        dcop, graph, mod, mem, load = self._setup()
        d = self._dist()
        agents_used = [a for a in d.agents if d.computations_hosted(a)]
        assert len(agents_used) == 1

    def test_capacity_forces_cheapest_split(self):
        # capacity 30 fits 3 of the 5 computations: the optimum cuts ONE
        # factor-graph edge (e.g. x,c1,y | c2,z), never more
        dcop, graph, mod, mem, load = self._setup()
        agents = [AgentDef(f"a{i}", capacity=30) for i in (1, 2)]
        d = mod.distribute(
            graph, agents, computation_memory=mem,
            communication_load=load,
        )
        cut = 0
        for node in graph.nodes:
            for neigh in node.neighbors:
                if d.agent_for(node.name) != d.agent_for(neigh):
                    cut += 1
        assert cut == 2  # each edge counted from both endpoints
