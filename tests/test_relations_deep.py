"""Relation-algebra depth tests, modeled on the reference's coverage map
(/root/reference/tests/unit/test_dcop_relations.py, ~2000 LoC): per-class
slicing, serialization round-trips, hashing/equality, join/projection
pinned against brute force, conditional relations, and the helper
utilities (count_var_match, is_compatible, find_dependent_relations,
add_var_to_rel)."""

import numpy as np
import pytest

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import (
    AsNAryFunctionRelation,
    ConditionalRelation,
    NAryFunctionRelation,
    NAryMatrixRelation,
    UnaryBooleanRelation,
    UnaryFunctionRelation,
    ZeroAryRelation,
    add_var_to_rel,
    assignment_cost,
    constraint_from_str,
    count_var_match,
    filter_assignment_dict,
    find_arg_optimal,
    find_dependent_relations,
    is_compatible,
    join,
    projection,
)
from pydcop_tpu.utils.expressions import ExpressionFunction
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr


@pytest.fixture
def d3():
    return Domain("d", "", [0, 1, 2])


class TestZeroAryRelation:
    def test_properties_and_value(self):
        r = ZeroAryRelation("z", 42.0)
        assert r.name == "z"
        assert r.arity == 0
        assert list(r.dimensions) == []
        assert r.get_value_for_assignment({}) == 42.0

    def test_slicing_on_no_var_is_ok(self):
        r = ZeroAryRelation("z", 42.0)
        s = r.slice({})
        assert s.get_value_for_assignment({}) == 42.0

    def test_repr_roundtrip_and_hash(self):
        r = ZeroAryRelation("z", 42.0)
        r2 = from_repr(simple_repr(r))
        assert r2 == r
        assert hash(r) == hash(ZeroAryRelation("z", 42.0))
        assert hash(r) != hash(ZeroAryRelation("z", 43.0))


class TestUnaryFunctionRelation:
    def test_value_and_expression(self, d3):
        v = Variable("v", d3)
        r = UnaryFunctionRelation("u", v, lambda x: x * 2)
        assert r.arity == 1
        assert r.get_value_for_assignment({"v": 2}) == 4
        re = UnaryFunctionRelation("u", v, ExpressionFunction("v + 1"))
        assert re.expression == "v + 1"
        assert re.get_value_for_assignment({"v": 2}) == 3

    def test_slicing(self, d3):
        v = Variable("v", d3)
        r = UnaryFunctionRelation("u", v, lambda x: x * 2)
        s = r.slice({"v": 1})
        assert s.arity == 0
        assert s.get_value_for_assignment({}) == 2
        with pytest.raises((ValueError, KeyError)):
            r.slice({"nope": 1})

    def test_eq_not_eq(self, d3):
        v = Variable("v", d3)
        f = ExpressionFunction("v * 2")
        assert UnaryFunctionRelation("u", v, f) == UnaryFunctionRelation(
            "u", v, ExpressionFunction("v * 2")
        )
        assert UnaryFunctionRelation("u", v, f) != UnaryFunctionRelation(
            "u2", v, f
        )

    def test_expression_repr_roundtrip(self, d3):
        v = Variable("v", d3)
        r = UnaryFunctionRelation("u", v, ExpressionFunction("v * 2"))
        # unary relations tabulate for transport: values survive exactly
        r2 = from_repr(simple_repr(r.tabulate()))
        for val in d3.values:
            assert r2.get_value_for_assignment(
                {"v": val}
            ) == r.get_value_for_assignment({"v": val})


class TestUnaryBooleanRelation:
    def test_truthiness(self, d3):
        v = Variable("v", d3)
        r = UnaryBooleanRelation("b", v)
        assert r.get_value_for_assignment({"v": 0}) == 0
        assert r.get_value_for_assignment({"v": 2}) == 1


class TestNAryFunctionRelation:
    def test_positional_and_kwargs_functions(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        rk = NAryFunctionRelation(lambda x, y: x + 10 * y, [x, y])
        assert rk.get_value_for_assignment({"x": 1, "y": 2}) == 21
        rp = NAryFunctionRelation(
            lambda a, b: a - b, [x, y], f_kwargs=False
        )
        assert rp.get_value_for_assignment({"x": 2, "y": 1}) == 1

    def test_expression_scope(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        r = NAryFunctionRelation(
            ExpressionFunction("x + 2 * y"), [x, y], name="e"
        )
        assert r.expression == "x + 2 * y"
        assert r.get_value_for_assignment({"x": 1, "y": 2}) == 5

    def test_slice_fixes_and_keeps(self, d3):
        x, y, z = (Variable(n, d3) for n in "xyz")
        r = NAryFunctionRelation(
            ExpressionFunction("x + 10*y + 100*z"), [x, y, z]
        )
        s = r.slice({"y": 2})
        assert sorted(s.scope_names) == ["x", "z"]
        assert s.get_value_for_assignment({"x": 1, "z": 1}) == 121

    def test_serialization_requires_expression(self, d3):
        x = Variable("x", d3)
        r = NAryFunctionRelation(lambda x: x, [x], name="lam")
        with pytest.raises(TypeError):
            simple_repr(r)
        re = NAryFunctionRelation(ExpressionFunction("x * 3"), [x], name="e")
        r2 = from_repr(simple_repr(re))
        assert r2.get_value_for_assignment({"x": 2}) == 6
        assert r2.name == "e"

    def test_decorator(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)

        @AsNAryFunctionRelation(x, y)
        def my_rel(x, y):
            return x * y

        assert my_rel.name == "my_rel"
        assert sorted(my_rel.scope_names) == ["x", "y"]
        assert my_rel.get_value_for_assignment({"x": 2, "y": 2}) == 4


class TestNAryMatrixRelation:
    def test_init_zero_default(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        r = NAryMatrixRelation([x, y])
        assert r.matrix.shape == (3, 3)
        assert (r.matrix == 0).all()

    def test_init_shape_validation(self, d3):
        x = Variable("x", d3)
        with pytest.raises(ValueError):
            NAryMatrixRelation([x], np.zeros((2,)))

    def test_get_value_as_list_and_dict(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        m = np.arange(9, dtype=float).reshape(3, 3)
        r = NAryMatrixRelation([x, y], m)
        assert r.get_value_for_assignment({"x": 1, "y": 2}) == 5.0
        assert r.get_value_for_assignment([1, 2]) == 5.0
        assert r(x=2, y=0) == 6.0

    def test_set_value_is_immutable_update(self, d3):
        x = Variable("x", d3)
        r = NAryMatrixRelation([x])
        r2 = r.set_value_for_assignment({"x": 1}, 8.5)
        assert r.get_value_for_assignment({"x": 1}) == 0
        assert r2.get_value_for_assignment({"x": 1}) == 8.5

    def test_slice_one_and_two_vars(self, d3):
        x, y, z = (Variable(n, d3) for n in "xyz")
        m = np.arange(27, dtype=float).reshape(3, 3, 3)
        r = NAryMatrixRelation([x, y, z], m)
        s1 = r.slice({"y": 1})
        assert s1.scope_names == ["x", "z"]
        assert s1.get_value_for_assignment({"x": 2, "z": 0}) == m[2, 1, 0]
        s2 = r.slice({"x": 0, "z": 2})
        assert s2.scope_names == ["y"]
        assert s2.get_value_for_assignment({"y": 1}) == m[0, 1, 2]
        with pytest.raises(ValueError):
            r.slice({"w": 0})

    def test_from_function_relation(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        f = NAryFunctionRelation(ExpressionFunction("x * 3 + y"), [x, y])
        m = NAryMatrixRelation.from_func_relation(f)
        for a in d3.values:
            for b in d3.values:
                assert m.get_value_for_assignment(
                    {"x": a, "y": b}
                ) == a * 3 + b

    def test_repr_roundtrip_and_eq(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        m = np.arange(9, dtype=float).reshape(3, 3)
        r = NAryMatrixRelation([x, y], m, name="m1")
        r2 = from_repr(simple_repr(r))
        assert r2 == r
        assert hash(r) == hash(
            NAryMatrixRelation([x, y], m + 1, name="m1")
        )  # hash on name+scope only; eq still distinguishes
        assert r != NAryMatrixRelation([x, y], m + 1, name="m1")


class TestConditionalRelation:
    def _rels(self, d3):
        c = Variable("c", d3)
        x = Variable("x", d3)
        condition = UnaryBooleanRelation("cond", c)
        consequence = UnaryFunctionRelation(
            "cons", x, ExpressionFunction("x * 10")
        )
        return c, x, ConditionalRelation(condition, consequence)

    def test_union_scope_and_value(self, d3):
        c, x, r = self._rels(d3)
        assert sorted(r.scope_names) == ["c", "x"]
        assert r.get_value_for_assignment({"c": 0, "x": 2}) == 0
        assert r.get_value_for_assignment({"c": 1, "x": 2}) == 20

    def test_slice_condition_var_collapses(self, d3):
        c, x, r = self._rels(d3)
        off = r.slice({"c": 0})
        # condition false: constant 0 over x
        vals = {
            off.get_value_for_assignment({"x": v})
            for v in d3.values
            if "x" in off.scope_names
        } or {off.get_value_for_assignment({})}
        assert vals == {0}

    def test_tabulated_matches(self, d3):
        c, x, r = self._rels(d3)
        m = r.tabulate()
        for cv in d3.values:
            for xv in d3.values:
                assert m.get_value_for_assignment(
                    {"c": cv, "x": xv}
                ) == r.get_value_for_assignment({"c": cv, "x": xv})


class TestJoinProjection:
    def test_join_matches_brute_force(self, d3):
        x, y, z = (Variable(n, d3) for n in "xyz")
        rng = np.random.default_rng(0)
        r1 = NAryMatrixRelation([x, y], rng.uniform(0, 9, (3, 3)))
        r2 = NAryMatrixRelation([y, z], rng.uniform(0, 9, (3, 3)))
        j = join(r1, r2)
        assert sorted(j.scope_names) == ["x", "y", "z"]
        for a in d3.values:
            for b in d3.values:
                for c in d3.values:
                    assert j.get_value_for_assignment(
                        {"x": a, "y": b, "z": c}
                    ) == pytest.approx(
                        r1.get_value_for_assignment({"x": a, "y": b})
                        + r2.get_value_for_assignment({"y": b, "z": c})
                    )

    def test_join_disjoint_scopes(self, d3):
        x, z = Variable("x", d3), Variable("z", d3)
        r1 = NAryMatrixRelation([x], np.array([1.0, 2, 3]))
        r2 = NAryMatrixRelation([z], np.array([10.0, 20, 30]))
        j = join(r1, r2)
        assert j.get_value_for_assignment({"x": 1, "z": 2}) == 32

    def test_projection_min_max(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        m = np.array([[4.0, 1, 7], [2, 9, 5], [8, 3, 6]])
        r = NAryMatrixRelation([x, y], m)
        pmin = projection(r, y, "min")
        assert pmin.scope_names == ["x"]
        np.testing.assert_array_equal(pmin.matrix, m.min(axis=1))
        pmax = projection(r, x, "max")
        np.testing.assert_array_equal(pmax.matrix, m.max(axis=0))
        with pytest.raises(ValueError):
            projection(r, Variable("w", d3))

    def test_projection_to_scalar(self, d3):
        x = Variable("x", d3)
        r = NAryMatrixRelation([x], np.array([3.0, 1, 2]))
        p = projection(r, x, "min")
        assert p.arity == 0
        assert p.get_value_for_assignment({}) == 1.0


class TestHelpers:
    def test_count_var_match(self, d3):
        xs = [Variable(f"x{i}", d3) for i in range(3)]
        r = NAryFunctionRelation(lambda x0, x1, x2: 0, xs, name="r3")
        assert count_var_match([], r) == 0
        assert count_var_match(["x0"], r) == 1
        assert count_var_match(["x0", "x1"], r) == 2
        assert count_var_match(["x0", "x1", "x2", "other"], r) == 3

    def test_is_compatible(self):
        assert is_compatible({"a": 1}, {"b": 2})
        assert is_compatible({"a": 1, "b": 2}, {"b": 2, "c": 3})
        assert not is_compatible({"a": 1, "b": 2}, {"b": 3})
        assert is_compatible({}, {"a": 1})

    def test_filter_assignment_dict(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        out = filter_assignment_dict({"x": 1, "y": 2, "w": 9}, [x, y])
        assert out == {"x": 1, "y": 2}

    def test_find_dependent_relations(self, d3):
        x, y, z = (Variable(n, d3) for n in "xyz")
        r1 = constraint_from_str("r1", "x + y", [x, y])
        r2 = constraint_from_str("r2", "y + z", [y, z])
        assert find_dependent_relations(x, [r1, r2]) == [r1]
        assert find_dependent_relations(y, [r1, r2]) == [r1, r2]
        assert find_dependent_relations(Variable("w", d3), [r1, r2]) == []

    def test_find_dependent_with_external_assignment(self, d3):
        # a conditional whose scope collapses once the (external) condition
        # variable is assigned no longer counts as dependent
        c, x = Variable("c", d3), Variable("x", d3)
        cond = ConditionalRelation(
            UnaryBooleanRelation("b", c),
            UnaryFunctionRelation("u", x, ExpressionFunction("x")),
        )
        only_x = UnaryFunctionRelation(
            "ux", x, ExpressionFunction("x * 2")
        )
        deps = find_dependent_relations(x, [cond, only_x])
        assert deps == [cond, only_x]
        # with c assigned, the conditional still depends on x (its scope
        # after slicing c keeps x), so both remain
        deps2 = find_dependent_relations(
            x, [cond, only_x], ext_var_assignment={"c": 1}
        )
        assert deps2 == [cond, only_x]
        # but slicing x out of the unary leaves nothing: not dependent on c
        assert find_dependent_relations(
            c, [only_x], ext_var_assignment={"x": 0}
        ) == []

    def test_add_var_to_rel(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        base = NAryMatrixRelation(
            [x], np.array([1.0, 2, 3]), name="base"
        )
        extended = add_var_to_rel(
            "ext", base, y, lambda cost, val: cost + 100 * val
        )
        assert sorted(extended.scope_names) == ["x", "y"]
        assert extended.get_value_for_assignment({"x": 2, "y": 1}) == 103

    def test_assignment_cost_and_find_arg_optimal(self, d3):
        x, y = Variable("x", d3), Variable("y", d3)
        m = np.array([[4.0, 1, 7], [2, 9, 5], [8, 3, 6]])
        r = NAryMatrixRelation([x, y], m)
        assert assignment_cost({"x": 1, "y": 2}, [r]) == 5.0
        vals, cost = find_arg_optimal(
            x, r.slice({"y": 1}), mode="min"
        )
        assert cost == 1.0 and vals == [0]
