"""Infrastructure tests (SURVEY.md §4 tier 1-2): message substrate, agents
with InProcessCommunicationLayer, discovery, orchestration end-to-end —
"multi-node without a real cluster" exactly like the reference's
infrastructure-in-process tier."""

import threading
import time

import pytest

pytest.importorskip("jax")

from pydcop_tpu.dcop import (  # noqa: E402
    DCOP,
    AgentDef,
    Domain,
    Variable,
    constraint_from_str,
)
from pydcop_tpu.infrastructure import (  # noqa: E402
    Agent,
    ComputationException,
    InProcessCommunicationLayer,
    Message,
    MessagePassingComputation,
    MSG_MGT,
    SynchronousComputationMixin,
    event_bus,
    message_type,
    register,
)
from pydcop_tpu.infrastructure.run import (  # noqa: E402
    run_local_thread_dcop,
    solve,
)
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr  # noqa: E402


def coloring_dcop(n_agents=3):
    d = Domain("colors", "", ["R", "G", "B"])
    x, y, z = Variable("x", d), Variable("y", d), Variable("z", d)
    dcop = DCOP("chain")
    dcop += constraint_from_str("c1", "10 if x == y else 0", [x, y])
    dcop += constraint_from_str("c2", "10 if y == z else 0", [y, z])
    dcop.add_agents(
        [AgentDef(f"a{i}", capacity=100) for i in range(n_agents)]
    )
    return dcop


# ---------------------------------------------------------------------------
# tier 1: substrate units
# ---------------------------------------------------------------------------


class TestMessageType:
    def test_fields_and_size(self):
        Msg = message_type("test_msg_a", ["value", "stuff"])
        m = Msg(value=[1, 2, 3], stuff="x")
        assert m.type == "test_msg_a"
        assert m.value == [1, 2, 3]
        assert m.size == 4  # len([1,2,3]) + len("x")

    def test_serialization_roundtrip(self):
        Msg = message_type("test_msg_b", ["value"])
        m = Msg(value=42)
        m2 = from_repr(simple_repr(m))
        assert m2 == m and m2.value == 42

    def test_conflicting_redefinition_rejected(self):
        message_type("test_msg_c", ["a"])
        with pytest.raises(ValueError):
            message_type("test_msg_c", ["a", "b"])

    def test_management_message_taxonomy_roundtrips(self):
        # round-3 verdict item 5: every management message the control
        # plane exchanges must survive simple_repr serialization — the
        # process/HTTP topology ships them as JSON (the reference pins
        # this in tests/unit/test_dcop_serialization.py for its taxonomy)
        from pydcop_tpu.infrastructure import discovery as dsc
        from pydcop_tpu.infrastructure import orchestrator as orc
        from pydcop_tpu.infrastructure.computations import (
            SynchronizationMsg,
        )

        samples = [
            orc.DeployMessage(comp_def={"name": "x", "algo": "dsa"}),
            orc.RunAgentMessage(computations=["x", "y"]),
            orc.PauseMessage(computations=None),
            orc.ResumeMessage(computations=["x"]),
            orc.StopAgentMessage(forced=False),
            orc.AgentRemovedMessage(reason="scenario"),
            orc.RegisterAgentMessage(agent="a1", address="tcp://h:1"),
            orc.DeployedMessage(agent="a1", computations=["x"]),
            orc.ValueChangeMessage(
                computation="x", value=2, cost=1.5, cycle=3
            ),
            orc.CycleChangeMessage(cycle=4, cost=10.0),
            orc.MetricsMessage(agent="a1", metrics={"count": {"x": 1}}),
            orc.ComputationFinishedMessage(computation="x"),
            orc.AgentStoppedMessage(agent="a1", metrics={"t": 0.5}),
            orc.ReplicateComputationsMessage(
                k=2, agents=["a1", "a2"], mode="distributed",
                agent_defs=None, round=1,
            ),
            orc.ComputationReplicatedMessage(
                agent="a1", replica_hosts={"x": ["a2", "a3"]}, round=1
            ),
            orc.SetupRepairMessage(
                repair_info={"orphans": ["x"], "round": 1}
            ),
            orc.RepairReadyMessage(
                agent="a1", computations=["x"], round=1
            ),
            orc.RepairRunMessage(),
            orc.RepairDoneMessage(agent="a1", selected=["x"], round=1),
            dsc.PublishAgentMessage(agent="a1", address="tcp://h:1"),
            dsc.UnpublishAgentMessage(agent="a1"),
            dsc.PublishComputationMessage(
                computation="x", agent="a1", address="tcp://h:1"
            ),
            dsc.UnpublishComputationMessage(computation="x"),
            dsc.PublishReplicaMessage(replica="x", agent="a2"),
            dsc.UnpublishReplicaMessage(replica="x", agent="a2"),
            dsc.SubscribeMessage(
                kind="agent", name=None, subscribe=True
            ),
            SynchronizationMsg(cycle_id=7),
        ]
        for msg in samples:
            back = from_repr(simple_repr(msg))
            assert type(back) is type(msg), msg.type
            assert back.type == msg.type
            for field in type(msg)._repr_fields:
                assert getattr(back, field) == getattr(msg, field), (
                    msg.type, field,
                )


class Echo(MessagePassingComputation):
    def __init__(self, name):
        super().__init__(name)
        self.received = []

    @register("ping")
    def _on_ping(self, sender, msg, t):
        self.received.append((sender, msg.content))
        self.post_msg(sender, Message("pong", msg.content))

    @register("pong")
    def _on_pong(self, sender, msg, t):
        self.received.append((sender, msg.content))


class TestComputation:
    def test_handler_dispatch(self):
        c = Echo("e1")
        sent = []
        c.message_sender = lambda s, d, m, p: sent.append((s, d, m))
        c.start()
        c.on_message("other", Message("ping", 42), 0.0)
        assert c.received == [("other", 42)]
        assert sent and sent[0][1] == "other" and sent[0][2].type == "pong"

    def test_unknown_message_raises(self):
        c = Echo("e2")
        with pytest.raises(ComputationException):
            c.on_message("other", Message("nope", None), 0.0)

    def test_pause_buffers_messages(self):
        c = Echo("e3")
        sent = []
        c.message_sender = lambda s, d, m, p: sent.append(d)
        c.start()
        c.pause(True)
        c.on_message("other", Message("ping", 1), 0.0)
        assert c.received == []
        c.pause(False)
        assert c.received == [("other", 1)] and sent == ["other"]


class SyncPair(SynchronousComputationMixin, MessagePassingComputation):
    def __init__(self, name, neighbor):
        super().__init__(name)
        self.neighbor = neighbor
        self.cycles_seen = []

    def synchronized_neighbors(self):
        return [self.neighbor]

    def on_start(self):
        self.start_cycle()
        self.post_sync_msg(self.neighbor, Message("tick", 0))

    @register("tick")
    def _on_tick(self, sender, msg, t):
        self.on_sync_message(sender, msg, t)

    @register("_sync")
    def _on_sync(self, sender, msg, t):
        self.on_sync_message(sender, msg, t)

    def on_new_cycle(self, messages, cycle_id):
        self.cycles_seen.append(cycle_id)
        if cycle_id < 3:
            self.post_sync_msg(self.neighbor, Message("tick", cycle_id))


class TestSynchronousMixin:
    def test_cycle_progression(self):
        # queued wiring like the agent loop: deliveries happen after both
        # computations started, never reentrantly
        a, b = SyncPair("a", "b"), SyncPair("b", "a")
        qa, qb = [], []
        a.message_sender = lambda s, d, m, p: qb.append((s, m))
        b.message_sender = lambda s, d, m, p: qa.append((s, m))
        a.start_cycle()
        b.start_cycle()
        a.start()
        b.start()
        for _ in range(50):
            if not qa and not qb:
                break
            if qb:
                s, m = qb.pop(0)
                b.on_message(s, m, 0.0)
            if qa:
                s, m = qa.pop(0)
                a.on_message(s, m, 0.0)
        assert a.cycles_seen[:3] == [1, 2, 3]
        assert b.cycles_seen[:3] == [1, 2, 3]

    def test_double_message_detected(self):
        a = SyncPair("a", "b")
        a.message_sender = lambda *args: None
        a.start_cycle()
        m1, m2 = Message("tick", 0), Message("tick", 0)
        m1._cycle_id = 0
        m2._cycle_id = 0
        a._on_tick("b", m1, 0.0)
        # second message for the same cycle: protocol race
        a._cycle_msgs["b"] = m1  # keep buffer non-empty
        with pytest.raises(ComputationException):
            a.on_sync_message("b", m2, 0.0)

    def test_next_cycle_message_buffered_not_lost(self):
        # a fast neighbor's cycle-(c+1) message arrives before this node
        # finishes cycle c: it must be buffered and consumed by the next
        # round, not dropped or treated as current (reference
        # computations.py:698-725 semantics)
        a = SyncPair("a", "b")
        sent = []
        a.message_sender = lambda s, d, m, p: sent.append((d, m))
        a.start_cycle()
        ahead = Message("tick", "ahead")
        ahead._cycle_id = 1
        a.on_sync_message("b", ahead, 0.0)
        assert a.cycle_count == 0  # not advanced by a future message
        now = Message("tick", "now")
        now._cycle_id = 0
        a.on_sync_message("b", now, 0.0)
        # cycle 0 completed with "now"; the buffered "ahead" message is
        # already in the new current-cycle buffer
        assert a.cycles_seen == [1]
        assert a.current_cycle["b"].content == "ahead"
        # and completing cycle 1 needs nothing more from b
        assert a.cycle_count == 1

    def test_skew_beyond_one_cycle_raises(self):
        a = SyncPair("a", "b")
        a.message_sender = lambda *args: None
        a.start_cycle()
        far = Message("tick", 0)
        far._cycle_id = 2
        with pytest.raises(ComputationException, match="skew"):
            a.on_sync_message("b", far, 0.0)

    def test_padding_sent_to_silent_neighbors(self):
        # a node with nothing to say still closes the round for its
        # neighbors with a _sync padding message (SyncPair always speaks,
        # so use a silent variant)
        class Silent(SyncPair):
            def on_new_cycle(self, messages, cycle_id):
                self.cycles_seen.append(cycle_id)  # no send

        a = Silent("a", "b")
        sent = []
        a.message_sender = lambda s, d, m, p: sent.append((d, m))
        a.start_cycle()
        m = Message("tick", 0)
        m._cycle_id = 0
        a.on_sync_message("b", m, 0.0)
        pads = [(d, mm) for d, mm in sent if mm.type == "_sync"]
        assert len(pads) == 1
        assert pads[0][0] == "b"
        assert pads[0][1]._cycle_id == 1  # stamped with the NEW cycle
        assert [d for d, _ in sent] == ["b"]  # nothing else went out


# ---------------------------------------------------------------------------
# tier 2: agents + discovery in-process
# ---------------------------------------------------------------------------


class TestAgents:
    def test_two_agents_message_exchange(self):
        a1 = Agent("a1", InProcessCommunicationLayer())
        a2 = Agent("a2", InProcessCommunicationLayer())
        e1, e2 = Echo("e1"), Echo("e2")
        a1.add_computation(e1, publish=False)
        a2.add_computation(e2, publish=False)
        # wire routes manually (no directory in this test)
        a1.messaging.register_route("e2", "a2", a2.communication.address)
        a2.messaging.register_route("e1", "a1", a1.communication.address)
        a1.start()
        a2.start()
        e1.start()
        e2.start()
        e1.post_msg("e2", Message("ping", "hello"))
        deadline = time.time() + 2
        while time.time() < deadline and not e1.received:
            time.sleep(0.01)
        assert ("e1", "hello") in e2.received  # ping arrived
        assert ("e2", "hello") in e1.received  # pong came back
        a1.clean_shutdown()
        a2.clean_shutdown()
        a1.join()
        a2.join()

    def test_parked_message_sent_on_route_discovery(self):
        a1 = Agent("a1", InProcessCommunicationLayer())
        a2 = Agent("a2", InProcessCommunicationLayer())
        e1, e2 = Echo("p1"), Echo("p2")
        a1.add_computation(e1, publish=False)
        a2.add_computation(e2, publish=False)
        a1.start()
        a2.start()
        e1.start()
        e2.start()
        e1.post_msg("p2", Message("ping", 1))  # no route yet: parked
        time.sleep(0.1)
        assert e2.received == []
        a1.messaging.register_route("p2", "a2", a2.communication.address)
        a2.messaging.register_route("p1", "a1", a1.communication.address)
        deadline = time.time() + 2
        while time.time() < deadline and not e2.received:
            time.sleep(0.01)
        assert ("p1", 1) in e2.received
        a1.clean_shutdown()
        a2.clean_shutdown()

    def test_metrics_counts_external_messages(self):
        a1 = Agent("m1", InProcessCommunicationLayer())
        a2 = Agent("m2", InProcessCommunicationLayer())
        e1, e2 = Echo("q1"), Echo("q2")
        a1.add_computation(e1, publish=False)
        a2.add_computation(e2, publish=False)
        a1.messaging.register_route("q2", "m2", a2.communication.address)
        a2.messaging.register_route("q1", "m1", a1.communication.address)
        a1.start()
        a2.start()
        e1.start()
        e2.start()
        e1.post_msg("q2", Message("ping", 5))
        time.sleep(0.3)
        m = a1.metrics()
        assert m["count_ext_msg"].get("q1", 0) >= 1
        a1.clean_shutdown()
        a2.clean_shutdown()


# ---------------------------------------------------------------------------
# tier 3: full orchestrated run (thread topology)
# ---------------------------------------------------------------------------


class TestOrchestratedRun:
    def test_solve_through_runtime(self):
        dcop = coloring_dcop()
        assignment = solve(dcop, "dpop", "oneagent")
        vals = [assignment["x"], assignment["y"], assignment["z"]]
        assert vals[0] != vals[1] and vals[1] != vals[2]

    def test_full_lifecycle_and_metrics(self):
        dcop = coloring_dcop()
        collected = []
        orchestrator = run_local_thread_dcop(
            "dsa",
            dcop,
            "oneagent",
            n_cycles=20,
            seed=1,
            collector=collected.append,
        )
        try:
            orchestrator.deploy_computations()
            orchestrator.run(timeout=30)
            assert orchestrator.status == "FINISHED"
            assignment, cost = orchestrator.current_solution()
            assert set(assignment) == {"x", "y", "z"}
            metrics = orchestrator.end_metrics()
            assert metrics["status"] == "FINISHED"
            assert metrics["cycle"] == 20
            assert metrics["cost"] == cost
            # value readbacks arrived at the mgt computation as value_change
            deadline = time.time() + 2
            while time.time() < deadline and len(collected) < 3:
                time.sleep(0.02)
            comps = {
                c["computation"]
                for c in collected
                if c["event"] == "value_change"
            }
            assert comps == {"x", "y", "z"}
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()

    def test_metrics_request_poll_and_repair_acks(self):
        # the send half of the agents' metrics_request handler and the
        # receive half of the repair_ready/repair_done acks (the four
        # protocol holes graftlint's baseline carried until this release)
        from pydcop_tpu.dcop.scenario import DcopEvent, Scenario

        dcop = coloring_dcop()
        collected = []
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=5,
            collector=collected.append,
            collect_moment="period", collect_period=0.05,
        )
        try:
            orchestrator.deploy_computations()
            # the delay event keeps run() alive long enough for the
            # periodic poll to fire several times
            orchestrator.run(
                scenario=Scenario([DcopEvent("d", delay=0.4)]),
                timeout=30,
            )
            assert any(c["event"] == "metrics" for c in collected), (
                "collect_period poll produced no metrics events"
            )
            # the poll is de-registered once run() returns
            assert orchestrator.mgt._periodic == []
            # live metrics poll: every registered agent answers with a
            # MetricsMessage that lands in agent_metrics
            orchestrator.mgt.agent_metrics.clear()
            orchestrator.request_agent_metrics()
            deadline = time.time() + 5
            expected = set(orchestrator.mgt.registered_agents)
            while time.time() < deadline and set(
                orchestrator.mgt.agent_metrics
            ) < expected:
                time.sleep(0.02)
            assert set(orchestrator.mgt.agent_metrics) >= expected
            # repair handshake acks are recorded, not dropped, and the
            # armed barrier releases when every expected ack arrived
            from pydcop_tpu.infrastructure import orchestrator as orc

            orchestrator.mgt.expect_repair_acks(1)
            assert not orchestrator.mgt.all_repair_ready.is_set()
            rnd = orchestrator.mgt.repair_round
            orchestrator.mgt.on_message(
                "a1",
                orc.RepairReadyMessage(
                    agent="a1", computations=["x"], round=rnd
                ),
                0.0,
            )
            orchestrator.mgt.on_message(
                "a1",
                orc.RepairDoneMessage(
                    agent="a1", selected=["x"], round=rnd
                ),
                0.0,
            )
            assert orchestrator.mgt.repair_ready_agents == {"a1": ["x"]}
            assert orchestrator.mgt.repair_selected == {"a1": ["x"]}
            assert orchestrator.mgt.all_repair_ready.is_set()
            # re-arming clears the previous episode's acks and bumps
            # the round
            orchestrator.mgt.expect_repair_acks(2)
            assert orchestrator.mgt.repair_ready_agents == {}
            assert not orchestrator.mgt.all_repair_ready.is_set()
            assert orchestrator.mgt.repair_round == rnd + 1
            # a straggler's ack from the TIMED-OUT previous episode must
            # not count toward (or release) the new barrier — the exact
            # stale-epoch-ack class proto-stale-guard exists to catch
            orchestrator.mgt.on_message(
                "a2",
                orc.RepairReadyMessage(
                    agent="a2", computations=["y"], round=rnd
                ),
                0.0,
            )
            orchestrator.mgt.on_message(
                "a2",
                orc.RepairDoneMessage(
                    agent="a2", selected=["y"], round=rnd
                ),
                0.0,
            )
            assert orchestrator.mgt.repair_ready_agents == {}
            assert orchestrator.mgt.repair_selected == {}
            assert not orchestrator.mgt.all_repair_ready.is_set()
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()

    def test_repair_handshake_conversation_is_spoken(self):
        # graftproto's proto-unsent-message rule found setup_repair and
        # repair_run declared + handled but never POSTED: the PR-6
        # handlers were dead code.  A scenario removal must now drive
        # the full setup_repair -> repair_ready -> repair_run ->
        # repair_done conversation on the wire.
        from pydcop_tpu.dcop.scenario import (
            DcopEvent, EventAction, Scenario,
        )

        dcop = coloring_dcop()
        scenario = Scenario(
            [
                DcopEvent("e1", delay=0.1),
                DcopEvent(
                    "e2",
                    actions=[EventAction("remove_agent", agent="a2")],
                ),
            ]
        )
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=30, seed=0
        )
        try:
            orchestrator.deploy_computations()
            orphans = orchestrator.distribution.computations_hosted("a2")
            # replicate so the survivors hold replicas to claim: the
            # repair_ready ack names only orphans in the agent's own
            # replica store, not an echo of the orchestrator's list
            orchestrator.start_replication(k=1, timeout=15)
            replica_holders = {
                h
                for comp in orphans
                for h in orchestrator.mgt.replica_hosts.get(comp, [])
            }
            orchestrator.run(scenario=scenario, timeout=30)
            assert orchestrator.status == "FINISHED"
            survivors = {"a0", "a1"}
            # phase 1: every survivor acked setup_repair (repair_ready)
            # with exactly the orphans it holds replicas of, releasing
            # the barrier
            assert set(orchestrator.mgt.repair_ready_agents) == survivors
            acked_union = set()
            for agent, comps in (
                orchestrator.mgt.repair_ready_agents.items()
            ):
                assert set(comps) <= set(orphans), (agent, comps)
                if agent in replica_holders:
                    assert comps == sorted(orphans), (agent, comps)
                acked_union.update(comps)
            assert acked_union == set(orphans)
            assert orchestrator.mgt.all_repair_ready.is_set()
            # phase 3: repair_run went out and every survivor's
            # repair_done selection was recorded
            deadline = time.time() + 5
            while time.time() < deadline and set(
                orchestrator.mgt.repair_selected
            ) < survivors:
                time.sleep(0.02)
            assert set(orchestrator.mgt.repair_selected) == survivors
            # the handshake is part of the repair record
            metrics = orchestrator.end_metrics()
            assert metrics["repair_metrics"][0][
                "repair_ready_agents"
            ] == sorted(survivors)
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()

    def test_computation_finished_reaches_orchestrator(self):
        # the other dead conversation graftproto surfaced: finished()
        # was a hook nothing wrapped, so ComputationFinishedMessage —
        # declared and handled since the seed — was never constructed.
        dcop = coloring_dcop()
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=5
        )
        try:
            orchestrator.deploy_computations()
            assert orchestrator.mgt.ready_to_run.wait(5)
            agent = next(
                a for a in orchestrator._local_agents.values()
                if a.deployed
            )
            comp = agent.computation(agent.deployed[0])
            comp.finished()
            deadline = time.time() + 5
            while (
                time.time() < deadline
                and comp.name
                not in orchestrator.mgt._finished_computations
            ):
                time.sleep(0.02)
            assert (
                comp.name in orchestrator.mgt._finished_computations
            )
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()

    def test_deployment_readback_updates_hosted_computations(self):
        dcop = coloring_dcop()
        orchestrator = run_local_thread_dcop(
            "dpop", dcop, "oneagent", n_cycles=1
        )
        try:
            orchestrator.deploy_computations()
            # deployment confirmations are asynchronous: the ready_to_run
            # barrier is the reference's "all deployed" condition
            assert orchestrator.mgt.ready_to_run.wait(5)
            deployed = {
                c for comps in orchestrator.mgt.deployed.values()
                for c in comps
            }
            assert deployed == {"x", "y", "z"}
            orchestrator.run(timeout=30)
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()


class TestControlPlaneScale:
    """Pin the orchestrator's readback/registration cost at 10k variables
    (round-2 verdict item 10): the control plane must stay a small constant
    over the device solve as perf work lands."""

    def test_cycle_metrics_run_at_10k_vars(self):
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_graph_coloring,
        )
        from pydcop_tpu.dcop.objects import AgentDef

        dcop = generate_graph_coloring(10_000, 3, graph="grid", seed=1)
        dcop._agents_def.clear()
        dcop.add_agents([AgentDef(f"a{i}", capacity=10**9) for i in range(8)])
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "adhoc", n_cycles=5, seed=1,
            collect_moment="cycle_change",
        )
        try:
            orchestrator.deploy_computations()
            t0 = time.perf_counter()
            # registration of 10k computations: one mgt round-trip each
            assert orchestrator.mgt.ready_to_run.wait(120)
            registration = time.perf_counter() - t0
            t0 = time.perf_counter()
            orchestrator.run(timeout=240)
            run_wall = time.perf_counter() - t0
            assert orchestrator.status == "FINISHED"
            metrics = orchestrator.end_metrics()
            assert metrics["cycle"] == 5
            assert len(metrics["assignment"]) == 10_000
            # control-plane budget: registration and the solve+readback
            # (including 10k per-computation value readbacks) stay bounded
            assert registration < 90, registration
            assert run_wall < 120, run_wall
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()

    @pytest.mark.slow
    def test_full_runtime_at_1m_vars(self):
        # round-4 verdict item 8: the 1M-variable stretch through the
        # FULL runtime path (the bench's 1M config bypasses the
        # orchestrator via compile.direct).  Solo-machine walls measured
        # 2026-07-30: deploy+ready 77 s, run (compile + 3-cycle DSA +
        # 1M per-computation readbacks) 116 s — linear vs the 100k test
        # below (9 s deploy) after three control-plane fixes this round:
        # the delivery lock convoy, the O(hosted) periodic tick scan and
        # the O(n^2) run_computations name filter.  Bounds are ~3x the
        # measured walls to absorb CI load.
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_graph_coloring,
        )
        from pydcop_tpu.dcop.objects import AgentDef

        dcop = generate_graph_coloring(
            1_000_000, 3, graph="scalefree", m_edge=2, seed=1
        )
        dcop._agents_def.clear()
        dcop.add_agents(
            [AgentDef(f"a{i}", capacity=10**12) for i in range(8)]
        )
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "adhoc", n_cycles=3, seed=1
        )
        try:
            t0 = time.perf_counter()
            orchestrator.deploy_computations(timeout=300)
            assert orchestrator.mgt.ready_to_run.wait(300)
            registration = time.perf_counter() - t0
            t0 = time.perf_counter()
            orchestrator.run(timeout=480)
            run_wall = time.perf_counter() - t0
            assert orchestrator.status == "FINISHED"
            metrics = orchestrator.end_metrics()
            assert metrics["cycle"] == 3
            assert len(metrics["assignment"]) == 1_000_000
            assert registration < 300, registration
            assert run_wall < 480, run_wall
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()

    @pytest.mark.slow
    def test_cycle_metrics_run_at_100k_vars(self):
        # round-3 verdict item 5: the headline problem size through the
        # FULL orchestrator runtime path (registration, deployment acks,
        # device solve, per-computation readback), not just api.solve.
        # Deployment was O(n^2) before the incremental-ack fix: 308 s at
        # this size, now ~9 s
        from pydcop_tpu.commands.generators.graphcoloring import (
            generate_graph_coloring,
        )
        from pydcop_tpu.dcop.objects import AgentDef

        dcop = generate_graph_coloring(
            100_000, 3, graph="scalefree", m_edge=2, seed=1
        )
        dcop._agents_def.clear()
        dcop.add_agents(
            [AgentDef(f"a{i}", capacity=10**9) for i in range(8)]
        )
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "adhoc", n_cycles=3, seed=1,
            collect_moment="cycle_change",
        )
        try:
            t0 = time.perf_counter()
            orchestrator.deploy_computations(timeout=120)
            assert orchestrator.mgt.ready_to_run.wait(120)
            registration = time.perf_counter() - t0
            t0 = time.perf_counter()
            orchestrator.run(timeout=240)
            run_wall = time.perf_counter() - t0
            assert orchestrator.status == "FINISHED"
            metrics = orchestrator.end_metrics()
            assert metrics["cycle"] == 3
            assert len(metrics["assignment"]) == 100_000
            assert registration < 60, registration
            assert run_wall < 150, run_wall
        finally:
            orchestrator.stop_agents()
            orchestrator.stop()


class TestCheckpoint:
    def test_pytree_roundtrip(self, tmp_path):
        import jax.numpy as jnp
        import numpy as np

        from pydcop_tpu.utils.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        state = {
            "a": jnp.arange(6).reshape(2, 3),
            "b": (jnp.ones(4), jnp.zeros((2, 2), dtype=bool)),
        }
        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, state, metadata={"cycle": 12})
        restored, meta = load_checkpoint(p, like=state)
        assert meta["cycle"] == 12
        assert np.array_equal(restored["a"], state["a"])
        assert restored["b"][1].dtype == bool

    def test_structure_mismatch_rejected(self, tmp_path):
        import jax.numpy as jnp
        import pytest as _pytest

        from pydcop_tpu.utils.checkpoint import (
            CheckpointError,
            load_checkpoint,
            save_checkpoint,
        )

        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, {"a": jnp.ones(3)})
        with _pytest.raises(CheckpointError):
            load_checkpoint(p, like={"a": jnp.ones(3), "b": jnp.ones(2)})

    def test_leaf_shape_and_dtype_mismatch_rejected(self, tmp_path):
        # same leaf COUNT but different shapes/dtypes must not silently
        # restore corrupt solver state (ADVICE.md round 1)
        import jax.numpy as jnp
        import pytest as _pytest

        from pydcop_tpu.utils.checkpoint import (
            CheckpointError,
            load_checkpoint,
            save_checkpoint,
        )

        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, {"a": jnp.ones(3)})
        with _pytest.raises(CheckpointError):
            load_checkpoint(p, like={"a": jnp.ones(4)})
        with _pytest.raises(CheckpointError):
            load_checkpoint(p, like={"a": jnp.ones(3, dtype=jnp.int32)})

    def test_same_leaves_different_structure_warns(self, tmp_path, caplog):
        # same leaf shapes but different container structure: restorable
        # (leaves validated), but the repr mismatch is surfaced as a
        # warning — str(PyTreeDef) is not stable across jax versions, so
        # it cannot be a hard error
        import logging

        import jax.numpy as jnp

        from pydcop_tpu.utils.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, {"a": jnp.ones(3), "b": jnp.ones(2)})
        with caplog.at_level(logging.WARNING, "pydcop_tpu.checkpoint"):
            load_checkpoint(p, like=(jnp.ones(3), jnp.ones(2)))
        assert any("tree repr differs" in r.message for r in caplog.records)

    def test_maxsum_session_resume(self, tmp_path):
        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum

        dcop = coloring_dcop()
        s1 = DynamicMaxSum(dcop, seed=5)
        s1.run(15)
        p = str(tmp_path / "solver.npz")
        s1.save(p)
        r_cont = s1.run(10)

        # a fresh session restored from the checkpoint continues identically
        s2 = DynamicMaxSum(coloring_dcop(), seed=5)
        s2.restore(p)
        assert s2._cycles_done == 15
        r_resumed = s2.run(10)
        assert r_resumed.assignment == r_cont.assignment
        assert r_resumed.cycles == r_cont.cycles == 25

    def test_maxsum_session_restore_across_layouts(self, tmp_path):
        # a checkpoint taken under the pre-round-5 default ("edges": row
        # planes, no aux) must restore into a default-configured session
        # (auto -> lanes) — the planes are transposed into the session's
        # layout and the solve continues to the same result
        from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSum

        s_old = DynamicMaxSum(
            coloring_dcop(), params={"layout": "edges"}, seed=5
        )
        s_old.run(15)
        p = str(tmp_path / "old.npz")
        s_old.save(p)
        r_cont = s_old.run(10)

        s_new = DynamicMaxSum(coloring_dcop(), seed=5)  # default layout
        s_new.restore(p)
        assert s_new._cycles_done == 15
        r_resumed = s_new.run(10)
        assert r_resumed.cycles == r_cont.cycles == 25
        # identical math, different reduction order: cost parity
        assert r_resumed.cost == pytest.approx(r_cont.cost, rel=1e-6)


class TestUiServer:
    def _ws_connect(self, port):
        import base64
        import socket as sk

        conn = sk.create_connection(("127.0.0.1", port), timeout=3)
        key = base64.b64encode(b"0123456789abcdef").decode()
        conn.sendall(
            (
                f"GET / HTTP/1.1\r\nHost: localhost:{port}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        resp = b""
        while b"\r\n\r\n" not in resp:
            resp += conn.recv(1024)
        assert b"101" in resp.split(b"\r\n")[0]
        return conn

    def _ws_send_text(self, conn, text):
        import os as _os
        import struct

        data = text.encode()
        mask = _os.urandom(4)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(data))
        header = b"\x81" + struct.pack("!B", 0x80 | len(data)) + mask
        conn.sendall(header + masked)

    def _ws_read_text(self, conn):
        import struct

        head = conn.recv(2)
        n = head[1] & 0x7F
        if n == 126:
            n = struct.unpack("!H", conn.recv(2))[0]
        data = b""
        while len(data) < n:
            data += conn.recv(n - len(data))
        return data.decode()

    def test_ui_query_and_event_stream(self):
        import json as _json

        agent = Agent(
            "ui_agent", InProcessCommunicationLayer(), ui_port=18765
        )
        e = Echo("ui_echo")
        agent.add_computation(e, publish=False)
        agent.start()
        try:
            conn = self._ws_connect(18765)
            self._ws_send_text(conn, _json.dumps({"cmd": "agent"}))
            reply = _json.loads(self._ws_read_text(conn))
            assert reply["agent"] == "ui_agent"
            assert "ui_echo" in reply["computations"]
            self._ws_send_text(conn, _json.dumps({"cmd": "computations"}))
            reply = _json.loads(self._ws_read_text(conn))
            names = {c["name"] for c in reply["computations"]}
            assert "ui_echo" in names
            conn.close()
        finally:
            agent.clean_shutdown()
            agent.join()
            event_bus.enabled = False
            event_bus.reset()

    def test_event_stream_during_solve(self):
        # round-3 verdict item 9, end-to-end: a ws client stays connected
        # through a full thread-mode solve and receives the pushed
        # cycle/value events alongside answered state queries (the
        # reference ships a browser client, tests/utils/ws-client.html;
        # this is its python equivalent)
        import json as _json
        import socket as sk

        from pydcop_tpu.infrastructure.run import run_local_thread_dcop

        port = 18801
        orchestrator = run_local_thread_dcop(
            "dsa", coloring_dcop(3), distribution="oneagent",
            n_cycles=10, ui_port=port, delay=0.02,
        )
        try:
            conn = self._ws_connect(port)
            conn.settimeout(10)
            # state query answered while the runtime is live
            self._ws_send_text(conn, _json.dumps({"cmd": "agent"}))
            streamed = []
            reply = None
            orchestrator.deploy_computations()
            orchestrator.run(timeout=30)
            # drain frames until the solve's event stream shows up: the
            # query reply and pushed bus events interleave arbitrarily
            try:
                while len(streamed) < 3:
                    frame = _json.loads(self._ws_read_text(conn))
                    if "topic" in frame:
                        streamed.append(frame)
                    else:
                        reply = frame
            except (TimeoutError, sk.timeout):
                pass
            assert reply is not None and "computations" in reply
            topics = {f["topic"] for f in streamed}
            assert any(t.startswith("computations.") for t in topics), (
                streamed
            )
            conn.close()
        finally:
            orchestrator.stop_agents(5)
            orchestrator.stop()
            event_bus.enabled = False
            event_bus.reset()


class TestUiServerUnit:
    """Targeted coverage of the UiServer websocket plumbing (ISSUE 4
    satellite): the RFC-6455 handshake key derivation, text-frame
    encode/decode round-trips across all three length encodings, and
    bus-event fanout to a connected client — previously only exercised
    incidentally through the integration tests above."""

    def test_ws_accept_key_matches_rfc6455_sample(self):
        from pydcop_tpu.infrastructure.ui import _ws_accept_key

        # the worked example from RFC 6455 §1.3
        assert (
            _ws_accept_key("dGhlIHNhbXBsZSBub25jZQ==")
            == "s3pPLMBiTxaQ9kYGzzhZRbK+xOo="
        )

    def test_frame_encode_decode_roundtrip_all_length_ranges(self):
        from pydcop_tpu.infrastructure.ui import (
            _ws_encode_text,
            _ws_read_frame,
        )

        class FakeConn:
            """recv()-compatible view over an in-memory byte buffer."""

            def __init__(self, data):
                self._data = data

            def recv(self, n):
                chunk, self._data = self._data[:n], self._data[n:]
                return chunk

        # 7-bit, 16-bit and 64-bit payload length encodings
        for n in (1, 125, 126, 4000, 70_000):
            text = "x" * n
            frame = _ws_encode_text(text)
            assert _ws_read_frame(FakeConn(frame)) == text
        # unicode survives the round trip
        frame = _ws_encode_text("héllo ✓")
        assert _ws_read_frame(FakeConn(frame)) == "héllo ✓"
        # a close frame (opcode 0x8) reads as None
        close = b"\x88\x00"
        assert _ws_read_frame(FakeConn(close)) is None

    def test_bus_event_fanout_to_connected_client(self):
        import json as _json

        helper = TestUiServer()
        agent = Agent(
            "ui_unit", InProcessCommunicationLayer(), ui_port=18923
        )
        agent.start()
        try:
            conn = helper._ws_connect(18923)
            conn.settimeout(5)
            # wait until the server registered this client (the
            # handshake reply arrives before the accept-loop thread has
            # necessarily appended it to _clients)
            ui = agent.computation("_ui_ui_unit")
            deadline = time.perf_counter() + 5
            while not ui._clients and time.perf_counter() < deadline:
                time.sleep(0.01)
            assert ui._clients, "client never registered with UiServer"
            event_bus.send("computations.cycle.demo", {"cycle": 3})
            frame = _json.loads(helper._ws_read_text(conn))
            assert frame["topic"] == "computations.cycle.demo"
            assert "3" in frame["event"]
            conn.close()
        finally:
            agent.clean_shutdown()
            agent.join()
            event_bus.enabled = False
            event_bus.reset()
