"""graftucs protocol tests (ISSUE 11): quiet-network equivalence with the
centralized UCS oracle, capacity races under ChaosCommunicationLayer,
partial-k replication levels, retraction (k-decrease / capacity shrink /
migration), the control-plane-stays-live repair fix, and the combined
elasticity showcase (agent joins -> re-replication onto the newcomer -> a
chaos kill repairs onto it, bit-replayable from the chaos seed)."""

import random
import time

import pytest

pytest.importorskip("jax")

from pydcop_tpu.chaos import (  # noqa: E402
    ChaosController,
    FaultSchedule,
    KillEvent,
    MessageRule,
)
from pydcop_tpu.dcop import (  # noqa: E402
    DCOP,
    AgentDef,
    Domain,
    Variable,
    constraint_from_str,
)
from pydcop_tpu.dcop.scenario import (  # noqa: E402
    DcopEvent,
    EventAction,
    Scenario,
)
from pydcop_tpu.distribution.objects import Distribution  # noqa: E402
from pydcop_tpu.infrastructure.run import run_local_thread_dcop  # noqa: E402
from pydcop_tpu.replication import ucs_replica_hosts  # noqa: E402
from pydcop_tpu.telemetry import telemetry_off  # noqa: E402
from pydcop_tpu.telemetry.metrics import metrics_registry  # noqa: E402
from pydcop_tpu.telemetry.tracing import tracer  # noqa: E402


@pytest.fixture(autouse=True)
def _telemetry_teardown():
    yield
    telemetry_off()


def _counter_total(name: str) -> int:
    m = metrics_registry.get(name)
    if m is None:
        return 0
    return int(sum(v["value"] for v in m.snapshot()["values"]))


def _ring_dcop(n, agent_defs, name="ring"):
    d = Domain("colors", "", ["R", "G", "B"])
    vs = [Variable(f"v{i}", d) for i in range(n)]
    dcop = DCOP(name)
    for i in range(n):
        a, b = vs[i], vs[(i + 1) % n]
        dcop += constraint_from_str(
            f"c{i}", f"10 if {a.name} == {b.name} else 0", [a, b]
        )
    dcop.add_agents(agent_defs)
    return dcop, vs


def _stop(orchestrator):
    orchestrator.stop_agents(timeout=3)
    orchestrator.stop()


def _poll(predicate, timeout=30.0):
    """Wait for an eventually-consistent condition: commits/retractions
    are fire-and-forget to their receivers, so barrier release does not
    imply every ledger already converged.  Every post-barrier assertion
    in this file goes through here — a fixed ``time.sleep`` bounds the
    wait by WALL CLOCK, which a loaded tier-1 run blows through (the
    PR-12 retraction flake); polling the condition itself bounds it by
    the thing actually awaited, with the timeout only as a backstop."""
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


class TestQuietNetworkEquivalence:
    """Satellite 1: on a fault-free network with ample capacity, the
    distributed negotiation, the centralized local mode and the pure
    oracle function place IDENTICALLY (same cost model — owner-known
    routes + discovered hosting costs — same (cost, name) tie-breaks).
    This is what keeps ``replication_mode="local"`` a verified fast path
    instead of a silent deviation."""

    def _random_dcop(self, seed, n_agents):
        rng = random.Random(seed)
        names = [f"a{i}" for i in range(n_agents)]
        comp_names = [f"v{i}" for i in range(n_agents)]
        agents = []
        for name in names:
            routes = {
                other: round(rng.uniform(0.5, 3.0), 2)
                for other in names
                if other != name
            }
            hosting = {
                c: round(rng.uniform(0.0, 2.0), 2) for c in comp_names
            }
            agents.append(
                AgentDef(
                    name,
                    capacity=1000,
                    routes=routes,
                    hosting_costs=hosting,
                    default_hosting_cost=round(rng.uniform(0.0, 2.0), 2),
                )
            )
        dcop, _ = _ring_dcop(n_agents, agents, name=f"eq{seed}")
        return dcop

    def _placements(self, dcop, k, mode):
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=5, replication_mode=mode
        )
        try:
            orchestrator.deploy_computations()
            orchestrator.start_replication(k=k, timeout=20)
            return (
                {
                    c: list(h)
                    for c, h in orchestrator.mgt.replica_hosts.items()
                },
                orchestrator.distribution,
            )
        finally:
            _stop(orchestrator)

    @pytest.mark.parametrize("seed,k", [(1, 1), (2, 2), (3, 2)])
    def test_protocol_matches_centralized_oracle(self, seed, k):
        dcop = self._random_dcop(seed, n_agents=4)
        negotiated, dist = self._placements(dcop, k, "distributed")
        local, _ = self._placements(dcop, k, "local")

        # the pure-function oracle, computed with the OWNER's knowledge
        # model (own routes, 1.0 for other hops) like both modes
        expected = {}
        agent_names = list(dcop.agents)
        for comp in dist.computations:
            owner = dist.agent_for(comp)
            owner_def = dcop.agents[owner]

            def route_cost(a, b, _o=owner, _od=owner_def):
                return float(_od.route(b)) if a == _o else 1.0

            def hosting_cost(a, c):
                return float(dcop.agents[a].hosting_cost(c))

            expected[comp] = ucs_replica_hosts(
                owner, comp, k, agent_names, route_cost, hosting_cost
            )
        assert negotiated == expected
        assert local == expected


class TestCapacityRace:
    """Satellite 3: two owners race for the last slot on the same host
    under chaos delay/reorder — exactly one accept, one
    refusal-then-next-candidate, zero dead letters, replayable by seed."""

    def _build(self):
        d = Domain("colors", "", ["R", "G"])
        x, y = Variable("x", d), Variable("y", d)
        dcop = DCOP("race")
        dcop += constraint_from_str("c0", "10 if x == y else 0", [x, y])
        # footprint(dsa) = n_neighbors = 1.0 for both x and y.
        # owners are capacity-saturated by their own computation; h_cheap
        # has exactly ONE replica slot; h_exp has room but costs more
        dcop.add_agents(
            [
                AgentDef(
                    "o1", capacity=1,
                    routes={"h_cheap": 1.0, "h_exp": 3.0, "o2": 9.0},
                ),
                AgentDef(
                    "o2", capacity=1,
                    routes={"h_cheap": 1.0, "h_exp": 3.0, "o1": 9.0},
                ),
                AgentDef("h_cheap", capacity=1),
                AgentDef("h_exp", capacity=100),
            ]
        )
        dist = Distribution(
            {"o1": ["x"], "o2": ["y"], "h_cheap": [], "h_exp": []}
        )
        schedule = FaultSchedule(
            seed=5,
            events=[
                # stagger o2's opening visit so the race resolves
                # deterministically (o1 takes the last slot) while the
                # rest of the exchange still jitters under delay/reorder
                MessageRule(
                    action="delay", pattern="ucs_visit",
                    src="_replication_o2", p=1.0, count=1, seconds=0.15,
                ),
                MessageRule(
                    action="reorder", pattern="ucs_*", p=0.3,
                    seconds=0.02,
                ),
                # at-least-once delivery: a duplicated accept must be
                # ignored by the owner (not answered with a release that
                # would strand the commit)
                MessageRule(
                    action="duplicate", pattern="ucs_accept", p=1.0
                ),
            ],
        )
        return dcop, dist, schedule

    def _run_once(self):
        metrics_registry.enabled = True
        dcop, dist, schedule = self._build()
        controller = ChaosController(schedule)
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, dist, n_cycles=5, chaos=controller
        )
        try:
            orchestrator.deploy_computations()
            levels = orchestrator.start_replication(k=1, timeout=20)
            placements = {
                c: list(h)
                for c, h in orchestrator.mgt.replica_hosts.items()
            }
            dead = orchestrator.dead_letter_total()
        finally:
            _stop(orchestrator)
        counters = {
            n: _counter_total(f"replication.{n}")
            for n in ("visits", "accepts", "refusals", "visit_timeouts")
        }
        log = controller.event_log()
        telemetry_off()
        return levels, placements, dead, counters, log

    def test_one_accept_one_refusal_then_next_candidate(self):
        levels, placements, dead, counters, _log = self._run_once()
        # exactly one owner got the contended slot; the refused one moved
        # on to the expensive host — nobody stalled, nothing was lost
        assert placements == {"x": ["h_cheap"], "y": ["h_exp"]}
        assert levels == {"x": 1, "y": 1}
        # refusals: h_cheap refuses the losing owner, and the loser's
        # strict-tie probe of the other owner (path tie 2.0 via the 1.0
        # unknown-hop model) is refused on capacity before it commits
        # h_exp — the strict < commit rule visits on exact cost ties so
        # placements stay oracle-identical
        assert counters["refusals"] == 2
        assert counters["accepts"] == 2
        assert counters["visits"] == 4
        assert counters["visit_timeouts"] == 0
        assert dead == 0

    def test_replayable_by_seed(self):
        r1 = self._run_once()
        r2 = self._run_once()
        assert r1[4] == r2[4]  # bit-identical chaos event log
        assert r1[1] == r2[1]  # identical placements
        assert r1[3] == r2[3]  # identical protocol counters


class TestPartialK:
    """Satellite 2: when fewer than k hosts can accept, the achieved
    replication level is RECORDED per computation and the barrier passes —
    k > capacity used to look exactly like a stalled agent."""

    def test_more_k_than_agents(self):
        dcop, _ = _ring_dcop(
            3, [AgentDef(f"a{i}", capacity=100) for i in range(3)]
        )
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=5
        )
        try:
            orchestrator.deploy_computations()
            t0 = time.perf_counter()
            levels = orchestrator.start_replication(k=5, timeout=20)
            # no barrier timeout: partial k is an immediate result
            assert time.perf_counter() - t0 < 10
            assert levels == {"v0": 2, "v1": 2, "v2": 2}
            assert orchestrator.mgt.replicated_agents == {"a0", "a1", "a2"}
            block = orchestrator.watch_status()["replication"]
            assert block["ktarget"] == 5
            assert sorted(block["below_target"]) == ["v0", "v1", "v2"]
        finally:
            _stop(orchestrator)

    def test_capacity_exhausts_mid_round(self):
        d = Domain("colors", "", ["R", "G"])
        x, y = Variable("x", d), Variable("y", d)
        dcop = DCOP("partial")
        dcop += constraint_from_str("c0", "10 if x == y else 0", [x, y])
        dcop.add_agents(
            [
                AgentDef("o1", capacity=100),
                AgentDef("h1", capacity=1),  # one replica slot total
                AgentDef("h2", capacity=0),  # none
            ]
        )
        dist = Distribution({"o1": ["x", "y"], "h1": [], "h2": []})
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, dist, n_cycles=5
        )
        try:
            orchestrator.deploy_computations()
            levels = orchestrator.start_replication(k=2, timeout=20)
            # x (negotiated first) takes h1's only slot; y gets nothing
            assert levels == {"x": 1, "y": 0}
            assert orchestrator.mgt.replica_hosts["x"] == ["h1"]
            assert orchestrator.mgt.replica_hosts["y"] == []
        finally:
            _stop(orchestrator)

    def test_timeout_detail_names_agents_and_levels(self):
        from pydcop_tpu.infrastructure.orchestrator import (
            replication_timeout_detail,
        )

        s = replication_timeout_detail(
            2.0,
            expected={"a1", "a2"},
            acked={"a2"},
            levels={"x": 1, "y": 2},
            k=2,
        )
        assert "a1" in s
        assert "below the k-target 2" in s
        assert "'x': 1" in s
        assert "y" not in s  # y reached the target — not a culprit


class TestRetraction:
    """Replica retraction (reference remove_replica :950): placements can
    SHRINK — on k-target decrease, on capacity loss (most-expensive-first
    shedding) and on migration onto one's own replica host."""

    def _orchestrator(self, n=3, capacity=100):
        dcop, _ = _ring_dcop(
            n, [AgentDef(f"a{i}", capacity=capacity) for i in range(n)]
        )
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=5
        )
        orchestrator.deploy_computations()
        return orchestrator

    def test_k_decrease_retracts_surplus(self):
        metrics_registry.enabled = True
        orchestrator = self._orchestrator()
        try:
            assert orchestrator.start_replication(k=2, timeout=20) == {
                "v0": 2, "v1": 2, "v2": 2,
            }

            def stores():
                return sum(
                    len(a.replica_store)
                    for a in orchestrator._local_agents.values()
                )

            # commits are fire-and-forget: poll until every host applied
            assert _poll(lambda: stores() == 6), stores()
            levels = orchestrator.start_replication(k=1, timeout=20)
            assert levels == {"v0": 1, "v1": 1, "v2": 1}
            assert _poll(lambda: stores() == 3), stores()
            assert _poll(
                lambda: _counter_total("replication.retractions") >= 3
            ), _counter_total("replication.retractions")
            assert _poll(
                lambda: all(
                    len(holders) == 1
                    for holders in (
                        orchestrator.directory.directory.replicas.values()
                    )
                )
            ), dict(orchestrator.directory.directory.replicas)
        finally:
            _stop(orchestrator)

    def test_capacity_shrink_sheds_replicas(self):
        metrics_registry.enabled = True
        orchestrator = self._orchestrator()
        try:
            orchestrator.start_replication(k=1, timeout=20)
            # pick any replica host and shrink it to nothing
            comp, (host,) = next(
                iter(orchestrator.mgt.replica_hosts.items())
            )
            agent = orchestrator._local_agents[host]
            assert comp in agent.replica_store
            orchestrator.set_agent_capacity(host, 0.0)
            # the shed, the placement-view prune and the discovery
            # unpublish are all fire-and-forget: poll each condition
            # instead of sleeping a fixed wall-clock amount and hoping
            # the mgt thread got scheduled (the load flake)
            assert _poll(lambda: comp not in agent.replica_store)
            assert _poll(
                lambda: host not in orchestrator.mgt.replica_hosts[comp]
            ), orchestrator.mgt.replica_hosts[comp]
            assert _poll(
                lambda: orchestrator.mgt.replication_levels[comp] == 0
            ), orchestrator.mgt.replication_levels[comp]
            assert _poll(
                lambda: host not in (
                    orchestrator.directory.directory.replicas.get(
                        comp, set()
                    )
                )
            )
            assert _poll(
                lambda: _counter_total("replication.retractions") >= 1
            )
        finally:
            _stop(orchestrator)

    def test_migration_drops_own_replica(self):
        orchestrator = self._orchestrator()
        try:
            orchestrator.start_replication(k=1, timeout=20)
            # kill an owner: its computation repairs onto its (only)
            # replica holder, which must then drop the now-shadowed
            # replica — holding a replica of a computation you RUN is
            # pointless
            victim = "a0"
            (orphan,) = orchestrator.distribution.computations_hosted(
                victim
            )
            (holder,) = orchestrator.mgt.replica_hosts[orphan]
            orchestrator._remove_agent(victim)
            assert orchestrator.distribution.agent_for(orphan) == holder
            holder_agent = orchestrator._local_agents[holder]
            # same fire-and-forget shape as the capacity shed above:
            # poll the conditions, don't race a fixed sleep against them
            assert _poll(
                lambda: orphan not in holder_agent.replica_store
            )
            assert _poll(
                lambda: holder not in (
                    orchestrator.mgt.replica_hosts.get(orphan, [])
                )
            ), orchestrator.mgt.replica_hosts.get(orphan)
        finally:
            _stop(orchestrator)


class TestControlPlaneStaysLive:
    """The repair freeze must not pause the control plane itself: before
    graftucs, the blanket PauseMessage paused each agent's ``_mgt_``
    computation, which then buffered its own Resume — every post-repair
    control-plane interaction (stop acks, metrics polls, replication
    rounds) was silently wedged forever."""

    def test_mgt_survives_repair_and_resumes_algorithm_comps(self):
        dcop, _ = _ring_dcop(
            4, [AgentDef(f"a{i}", capacity=100) for i in range(4)]
        )
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=5
        )
        try:
            orchestrator.deploy_computations()
            orchestrator.start_replication(k=1, timeout=20)
            orchestrator._remove_agent("a3")
            time.sleep(0.3)
            for name, agent in orchestrator._local_agents.items():
                if name == "a3":
                    continue
                for comp in agent.computations:
                    assert not comp.is_paused, (name, comp.name)
            # the control plane actually answers after the repair: a
            # replication round completes and a metrics poll round-trips
            levels = orchestrator.start_replication(k=1, timeout=10)
            assert set(levels) == {"v0", "v1", "v2", "v3"}
            orchestrator.mgt.agent_metrics.clear()
            orchestrator.request_agent_metrics()
            deadline = time.perf_counter() + 5
            while (
                len(orchestrator.mgt.agent_metrics) < 3
                and time.perf_counter() < deadline
            ):
                time.sleep(0.02)
            assert len(orchestrator.mgt.agent_metrics) >= 3
        finally:
            _stop(orchestrator)


class TestRoundEpoch:
    def test_stale_round_ack_does_not_release_new_barrier(self):
        from pydcop_tpu.infrastructure.orchestrator import (
            ComputationReplicatedMessage,
        )

        dcop, _ = _ring_dcop(
            3, [AgentDef(f"a{i}", capacity=100) for i in range(3)]
        )
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=5
        )
        try:
            orchestrator.deploy_computations()
            orchestrator.start_replication(k=1, timeout=20)
            mgt = orchestrator.mgt
            # arm a new round, then replay an ack from the PREVIOUS one:
            # the barrier must not release (its agent's new negotiation
            # could still be running), but the placement view still merges
            mgt.expect_replication({"a0"}, k=1, mode="distributed")
            stale = ComputationReplicatedMessage(
                agent="a0", replica_hosts={"v9": ["a1"]},
                round=mgt.replication_round - 1,
            )
            mgt._on_replicated("_mgt_a0", stale, 0.0)
            assert not mgt.all_replicated.is_set()
            assert mgt.replica_hosts["v9"] == ["a1"]
            fresh = ComputationReplicatedMessage(
                agent="a0", replica_hosts={},
                round=mgt.replication_round,
            )
            mgt._on_replicated("_mgt_a0", fresh, 0.0)
            assert mgt.all_replicated.is_set()
        finally:
            _stop(orchestrator)


class TestWatchRendering:
    def test_watch_renders_replication_block(self):
        from pydcop_tpu.commands.watch import _render_frame

        status = {
            "status": "running",
            "replication": {
                "mode": "distributed", "ktarget": 2,
                "levels": {"x": 1, "y": 2}, "below_target": ["x"],
                "visits": 7, "refusals": 2, "retractions": 1,
                "visit_timeouts": 0,
            },
        }
        frame = _render_frame(status, {}, {})
        (line,) = [
            l for l in frame.splitlines() if l.startswith("replication:")
        ]
        assert "mode=distributed" in line
        assert "k=2" in line
        assert "visits=7" in line
        assert "refusals=2" in line
        assert "retractions=1" in line
        assert "BELOW TARGET: x" in line
        # no replication key -> no line (watch degrades cleanly)
        frame2 = _render_frame({"status": "running"}, {}, {})
        assert "replication:" not in frame2


class TestCombinedElasticity:
    """The showcase the reference left as a TODO (orchestrator.py:1032):
    an agent ARRIVES mid-run, the system re-replicates onto it via the
    negotiation protocol (retracting the displaced replicas), and a
    chaos-seeded kill of an original host then repairs its computations
    onto the newcomer — bit-replayable from the chaos seed, with the
    protocol counters and negotiation spans on the telemetry surface."""

    KILL_AT = 2.0

    def _run_once(self):
        telemetry_off()
        metrics_registry.enabled = True
        tracer.reset()
        tracer.enabled = True
        agents = [
            # originals host expensively; a3 has no spare capacity at
            # all, so visits to it are REFUSED (counter coverage)
            AgentDef("a0", capacity=100, default_hosting_cost=5.0),
            AgentDef("a1", capacity=100, default_hosting_cost=5.0),
            AgentDef("a2", capacity=100, default_hosting_cost=5.0),
            AgentDef("a3", capacity=0, default_hosting_cost=5.0),
        ]
        dcop, vs = _ring_dcop(4, agents)
        schedule = FaultSchedule(
            seed=11, events=[KillEvent("a1", at=self.KILL_AT)]
        )
        controller = ChaosController(schedule)
        scenario = Scenario(
            [
                DcopEvent("e1", delay=0.05),
                DcopEvent(
                    "e2",
                    actions=[EventAction("add_agent", agent="a_new")],
                ),
            ]
        )
        orchestrator = run_local_thread_dcop(
            "dsa", dcop, "oneagent", n_cycles=30, seed=0,
            chaos=controller,
        )
        out = {}
        try:
            orchestrator.deploy_computations()
            orchestrator.start_replication(k=1, timeout=20)
            out["initial_hosts"] = {
                c: list(h)
                for c, h in orchestrator.mgt.replica_hosts.items()
            }
            out["a1_comps"] = list(
                orchestrator.distribution.computations_hosted("a1")
            )
            orchestrator.run(scenario=scenario, timeout=60)
            out["status"] = orchestrator.status
            # the killed owner's computations migrate onto their replica
            # host, which then retracts the shadowed replicas — wait for
            # that (asynchronous) retraction to settle before snapshotting
            _poll(
                lambda: all(
                    "a_new" not in orchestrator.mgt.replica_hosts.get(c, [])
                    for c in out["a1_comps"]
                )
            )
            out["final_hosts"] = {
                c: list(h)
                for c, h in orchestrator.mgt.replica_hosts.items()
            }
            out["mapping"] = orchestrator.distribution.mapping
            out["assignment"], _ = orchestrator.current_solution()
            out["dead_letters"] = orchestrator.dead_letter_total()
            out["event_log"] = controller.event_log()
            out["replication_block"] = orchestrator.watch_status()[
                "replication"
            ]
            out["spans"] = [
                e
                for e in tracer.events()
                if e.get("name") == "replication.negotiate"
            ]
        finally:
            _stop(orchestrator)
            telemetry_off()
        return out

    def test_join_rereplicate_kill_repair_onto_newcomer(self):
        out = self._run_once()
        assert out["status"] == "FINISHED"
        # initial replicas sat on originals (the newcomer did not exist)
        for comp, hosts in out["initial_hosts"].items():
            assert hosts and all(h.startswith("a") for h in hosts)
            assert "a_new" not in hosts
        # re-replication moved EVERY replica onto the cheap newcomer —
        # displacing the incumbents exercises live retraction.  The
        # killed owner's computations then MIGRATED onto a_new, whose
        # own-replica retraction empties their host lists (a replica of a
        # computation you run is pointless)
        for comp, hosts in out["final_hosts"].items():
            if comp in out["a1_comps"]:
                assert hosts == [], (comp, hosts)
            else:
                assert hosts == ["a_new"], (comp, hosts)
        # the killed original's computations repaired ONTO the newcomer
        # (its replicas made it the only candidate)
        assert out["a1_comps"]
        for comp in out["a1_comps"]:
            assert comp in out["mapping"].get("a_new", []), out["mapping"]
        assert "a1" not in out["mapping"]
        # complete solution, nothing lost
        assert set(out["assignment"]) == {f"v{i}" for i in range(4)}
        assert out["dead_letters"] == 0
        # telemetry surface: counters + spans + /status block
        block = out["replication_block"]
        assert block["mode"] == "distributed"
        assert block["visits"] > 0
        assert block["refusals"] > 0  # a3 (capacity 0) refused visits
        assert block["retractions"] > 0  # displaced incumbents
        assert out["spans"], "no replication.negotiate spans recorded"
        span_args = out["spans"][0]["args"]
        assert {"comp", "owner", "k", "placed", "visits"} <= set(span_args)
        # the kill is in the chaos log at its scheduled time
        assert {
            "stream": "_timeline", "n": 0, "action": "kill",
            "agent": "a1", "at": self.KILL_AT,
        } in out["event_log"]

    def test_bit_replayable_from_seed(self):
        r1 = self._run_once()
        r2 = self._run_once()
        assert r1["event_log"] == r2["event_log"]
        assert r1["final_hosts"] == r2["final_hosts"]
        assert r1["mapping"] == r2["mapping"]
        assert r1["assignment"] == r2["assignment"]
